"""IVF-PQ: inverted-file index with product-quantized residual vectors.

Reference surface: raft::neighbors::ivf_pq — build (ivf_pq-inl.cuh:273 →
detail/ivf_pq_build.cuh:1729: kmeans_balanced coarse trainer :1828, random
rotation make_rotation_matrix :119, codebook training train_per_subset :392,
encode process_and_fill_codes :1319), search (detail/ivf_pq_search.cuh:731:
select_clusters :69 → LUT-based scan ivfpq_search_worker :420 →
select_k :586 → optional refine re-rank refine-inl.cuh:70); params
ivf_pq_types.hpp:36-264 (pq_bits 4..8, pq_dim, codebook per-subspace).

TPU design — the LUT scan rearranged so the per-probe work is additive
constants plus a *per-query-only* table:

    d²(q, x_j∈list l) ≈ |q - c_l|²                      (stage-1 coarse value)
                      + Σ_s −2·(Rq)_s·cb[s, code_js]    (query-only LUT A)
                      + Σ_s (2·(Rc_l)_s·cb[s, code_js]
                             + |cb[s, code_js]|²)        (b_sum: baked at build)

The reference rebuilds a LUT per (query, probe) from the rotated residual
(ivf_pq_search.cuh:420); splitting the residual LUT into A (query half) and
b_sum (list half, a per-entry scalar precomputed at build) removes the
per-probe LUT entirely: search-time work is one gemm for A, the stage-1
coarse gemm, and a code→A lookup. The lookup itself has two backends:

  * jnp gather (`take_along_axis`) — correct everywhere, the CPU/test oracle;
  * the Pallas list-centric kernel (ops/pq_scan.py) — queries batched as the
    MXU N-dimension against in-VMEM one-hot code blocks (used on TPU).

Codes are stored tightly bit-packed (pq_bits 4..8, pack_codes) in padded
dense lists like ivf_flat (XLA static shapes; kIndexGroupSize-aligned);
search reads an int8 RESIDUAL reconstruction cache (rot_dim bytes/entry,
see _decode_lists) through the strip kernel, with the exact per-pair
center term applied at the merge.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import obs
from raft_tpu.obs import compile as obs_compile
from raft_tpu.obs import roofline as obs_roofline
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import _filtering
from raft_tpu.neighbors import _packing
from raft_tpu.core.logger import get_logger
from raft_tpu.core.trace import traced
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.serialize import load_arrays, save_arrays
from raft_tpu.ops import distance as dist_mod
from raft_tpu.ops import linalg
from raft_tpu.ops.pq_scan import group_probed_pairs, pq_scan
from raft_tpu.ops.select_k import select_k
from raft_tpu.utils.tiling import map_row_tiles

_log = get_logger()

SUPPORTED_METRICS = ("sqeuclidean", "euclidean", "inner_product", "cosine")


@dataclass(frozen=True)
class IvfPqParams:
    """Build params (ivf_pq_types.hpp index_params analog)."""

    n_lists: int = 1024
    pq_dim: int = 0  # 0 = auto: dim/2 rounded up to a multiple of 8
    pq_bits: int = 8  # codebook size = 2**pq_bits, 4..8 like the reference
    # "subspace": one codebook per sub-dimension (codebook_gen::PER_SUBSPACE)
    # "cluster": one codebook per IVF list, shared across sub-dimensions
    # (codebook_gen::PER_CLUSTER, ivf_pq_types.hpp:36)
    codebook_kind: str = "subspace"
    metric: str = "sqeuclidean"
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    codebook_n_iters: int = 25
    # per-list occupancy cap: -1 = auto (4× mean, group-aligned), 0 = off
    # (_packing.spill_to_cap overflow policy)
    list_size_cap: int = -1
    # list padding granule: 0 = auto (_packing.auto_group_size)
    group_size: int = 0
    seed: int = 0

    def __post_init__(self):
        m = dist_mod.canonical_metric(self.metric)
        if m not in SUPPORTED_METRICS:
            raise ValueError(f"ivf_pq supports {SUPPORTED_METRICS}, got {self.metric!r}")
        object.__setattr__(self, "metric", m)
        if not 4 <= self.pq_bits <= 8:
            raise ValueError(f"pq_bits must be in [4, 8], got {self.pq_bits}")
        if self.codebook_kind not in ("subspace", "cluster"):
            raise ValueError(
                f"codebook_kind must be 'subspace'|'cluster', got "
                f"{self.codebook_kind!r}")


@jax.tree_util.register_pytree_node_class
@dataclass
class IvfPqIndex:
    """Coarse centers + rotation + per-subspace codebooks + packed code lists.

    ``b_sum`` carries the list-side half of the L2 LUT decomposition (zeros
    for inner-product metrics). ``list_ids[l, j] == -1`` marks padding.
    """

    centers: jax.Array  # (n_lists, dim) fp32 — unrotated, for stage 1
    rotation: jax.Array  # (rot_dim, rot_dim) orthogonal
    codebooks: jax.Array  # (pq_dim, n_codes, dsub) fp32
    list_codes: jax.Array  # (n_lists, max_list_size, pq_dim) uint8
    list_ids: jax.Array  # (n_lists, max_list_size) int32
    b_sum: jax.Array  # (n_lists, max_list_size) fp32
    # (n_lists, max_list_size, rot_dim) int8 strip-scan cache (+ host-side
    # float scale in ``decoded_scale``); None until the first strip search
    # (lazy: rot_dim bytes/slot, wasted on CPU/gather deployments). The
    # quantized-reconstruction analog of the reference's fp8-compressed LUT
    # (detail/ivf_pq_fp_8bit.cuh): only the cross term -2⟨q, x̂⟩ is
    # approximated — the ‖x̂‖² half rides exactly in b_sum.
    decoded: Optional[jax.Array]
    metric: str
    pq_bits: int
    # list padding granule used at build; extend() reuses it instead of
    # inferring from max_list_size (ADVICE.md round-2: inference can silently
    # flip the granule and change backend eligibility). 0 = unknown (legacy).
    group_size: int = 0
    decoded_scale: Optional[jax.Array] = None  # 0-d fp32 dequant scale
    # "subspace" (codebooks (pq_dim, n_codes, dsub)) or "cluster"
    # (codebooks (n_lists, n_codes, dsub), ivf_pq_types.hpp:36 PER_CLUSTER)
    codebook_kind: str = "subspace"
    pq_dim_hint: int = 0  # explicit pq_dim (cluster kind can't derive it)

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def pq_dim(self) -> int:
        return self.pq_dim_hint or self.codebooks.shape[0]

    @property
    def n_codes(self) -> int:
        return self.codebooks.shape[1]

    @property
    def max_list_size(self) -> int:
        return self.list_codes.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_ids >= 0))

    def list_sizes(self) -> jax.Array:
        return jnp.sum(self.list_ids >= 0, axis=1).astype(jnp.int32)

    def tree_flatten(self):
        return (
            self.centers, self.rotation, self.codebooks,
            self.list_codes, self.list_ids, self.b_sum, self.decoded,
            self.decoded_scale,
        ), (self.metric, self.pq_bits, self.group_size, self.codebook_kind,
            self.pq_dim_hint)

    @classmethod
    def tree_unflatten(cls, aux, children):
        (centers, rotation, codebooks, list_codes, list_ids, b_sum,
         decoded, decoded_scale) = children
        metric, pq_bits, group_size, codebook_kind, pq_dim_hint = aux
        return cls(centers, rotation, codebooks, list_codes, list_ids,
                   b_sum, decoded, metric, pq_bits, group_size,
                   decoded_scale=decoded_scale, codebook_kind=codebook_kind,
                   pq_dim_hint=pq_dim_hint)

    # -- persistence (ivf_pq_serialize.cuh analog) -------------------------
    def save(self, path) -> None:
        save_arrays(
            path,
            {"kind": "ivf_pq", "metric": self.metric, "pq_bits": self.pq_bits,
             "group_size": self.group_size,
             "codebook_kind": self.codebook_kind,
             "pq_dim_hint": self.pq_dim_hint},
            {
                "centers": self.centers,
                "rotation": self.rotation,
                "codebooks": self.codebooks,
                "list_codes": self.list_codes,
                "list_ids": self.list_ids,
                "b_sum": self.b_sum,
            },
        )

    @classmethod
    def load(cls, path) -> "IvfPqIndex":
        # `decoded` is derived data — recomputed here, never serialized
        meta, arrays = load_arrays(path)
        if meta.get("kind") != "ivf_pq":
            raise ValueError(f"not an ivf_pq index: {meta.get('kind')}")
        centers = jnp.asarray(arrays["centers"])
        rotation = jnp.asarray(arrays["rotation"])
        codebooks = jnp.asarray(arrays["codebooks"])
        list_codes = jnp.asarray(arrays["list_codes"])
        list_ids = jnp.asarray(arrays["list_ids"])
        return cls(
            centers, rotation, codebooks, list_codes, list_ids,
            jnp.asarray(arrays["b_sum"]), None,
            meta["metric"],
            int(meta["pq_bits"]),
            int(meta.get("group_size", 0)),
            codebook_kind=meta.get("codebook_kind", "subspace"),
            pq_dim_hint=int(meta.get("pq_dim_hint", 0)),
        )


# ---------------------------------------------------------------------------
# Build pieces
# ---------------------------------------------------------------------------


def _auto_pq_dim(dim: int) -> int:
    pq = max(1, dim // 2)
    return -(-pq // 8) * 8 if pq >= 8 else pq


def packed_width(pq_dim: int, pq_bits: int) -> int:
    """Bytes per encoded vector at ``pq_bits`` bits per sub-dimension."""
    return -(-pq_dim * pq_bits // 8)


def pack_codes(codes, pq_bits: int):
    """(…, pq_dim) uint8 codes → (…, ceil(pq_dim·bits/8)) tightly packed
    uint8 (ivf_pq_types.hpp stores pq_bits 4..8 packed; round-2 VERDICT
    Missing#3: one byte per sub-dim forfeited PQ's memory edge below 8
    bits). Little-endian bit order within the stream."""
    if pq_bits == 8:
        return codes
    pq_dim = codes.shape[-1]
    nbytes = packed_width(pq_dim, pq_bits)
    c32 = codes.astype(jnp.uint32)
    bit0 = jnp.arange(pq_dim, dtype=jnp.uint32) * pq_bits
    out = jnp.zeros(codes.shape[:-1] + (nbytes,), jnp.uint32)
    for b in range(2):  # a field spans at most 2 bytes for bits <= 8
        byte = (bit0 >> 3) + b
        shift = jnp.where(b == 0, bit0 & 7, 0)
        down = jnp.where(b == 0, 0, 8 - (bit0 & 7))
        part = jnp.where(b == 0, c32 << shift, c32 >> down) & 0xFF
        valid = byte < nbytes
        out = out.at[..., jnp.where(valid, byte, 0)].add(
            jnp.where(valid, part, 0))
    return out.astype(jnp.uint8)


def unpack_codes(packed, pq_dim: int, pq_bits: int):
    """Inverse of :func:`pack_codes` → (…, pq_dim) uint8."""
    if pq_bits == 8:
        return packed
    nbytes = packed.shape[-1]
    p32 = packed.astype(jnp.uint32)
    bit0 = jnp.arange(pq_dim, dtype=jnp.uint32) * pq_bits
    byte = bit0 >> 3
    r = bit0 & 7
    lo = jnp.take(p32, byte, axis=-1) >> r
    hi_byte = jnp.minimum(byte + 1, nbytes - 1)
    hi = jnp.take(p32, hi_byte, axis=-1) << (8 - r)
    hi = jnp.where(byte + 1 < nbytes, hi, 0)
    mask = (1 << pq_bits) - 1
    return ((lo | hi) & mask).astype(jnp.uint8)


# promoted to ops/linalg (round 17, with the SRHT rotation family); these
# re-export shims keep the long-standing public names importable from here
make_rotation_matrix = linalg.make_rotation_matrix
pad_rot = linalg.pad_rot


@functools.partial(jax.jit, static_argnames=("n_codes", "n_iters"))
def _train_codebooks(resid_sub, key, n_codes, n_iters):
    """Per-subspace Lloyd k-means (train_per_subset analog,
    detail/ivf_pq_build.cuh:392).

    resid_sub: (pq_dim, n_train, dsub) rotated residuals. Sequential
    `lax.map` over subspaces — each holds an (n_train, n_codes) distance
    block; mapping (not vmapping) keeps only one block live at a time.
    """
    pq_dim, n_train, dsub = resid_sub.shape

    def one_subspace(args):
        X, key = args
        # with-replacement init: valid even when n_train < n_codes (tiny
        # datasets leave dead codes, harmless), and avoids the O(n log n)
        # permutation program choice(replace=False) would compile
        rows = jax.random.randint(key, (n_codes,), 0, n_train)
        centers0 = X[rows]

        def step(_, centers):
            d2 = (
                dist_mod.sqnorm(X)[:, None]
                + dist_mod.sqnorm(centers)[None, :]
                - 2.0 * dist_mod.matmul_t(X, centers)
            )
            labels = jnp.argmin(d2, axis=1)
            sums = jax.ops.segment_sum(X, labels, num_segments=n_codes)
            counts = jax.ops.segment_sum(jnp.ones(n_train), labels, num_segments=n_codes)
            return jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers)

        return lax.fori_loop(0, n_iters, step, centers0)

    keys = jax.random.split(key, pq_dim)
    return lax.map(one_subspace, (resid_sub, keys))


def _encode(resid_rot, codebooks, chunk: int = 8192):
    """resid_rot (n, pq_dim, dsub) → codes (n, pq_dim) uint8: per-subspace
    nearest codebook entry (process_and_fill_codes analog,
    detail/ivf_pq_build.cuh:1319). Chunked over rows so the (chunk, pq_dim,
    n_codes) distance block stays bounded."""
    n = resid_rot.shape[0]
    cn = jnp.sum(codebooks * codebooks, axis=2)  # (s, c)

    def enc(chunk_rows):
        ip = jnp.einsum(
            "nsd,scd->nsc", chunk_rows, codebooks, preferred_element_type=jnp.float32
        )
        return jnp.argmin(cn[None] - 2.0 * ip, axis=2).astype(jnp.uint8)

    if n <= chunk:
        return enc(resid_rot)
    n_chunks = -(-n // chunk)
    pad = n_chunks * chunk - n
    padded = jnp.pad(resid_rot, ((0, pad), (0, 0), (0, 0)))
    out = lax.map(enc, padded.reshape(n_chunks, chunk, *resid_rot.shape[1:]))
    return out.reshape(-1, resid_rot.shape[1])[:n]


@functools.partial(jax.jit, static_argnames=("n_codes", "n_iters", "n_lists"))
def _train_codebooks_cluster(resid_sub, labels, key, n_codes, n_iters,
                             n_lists):
    """Per-CLUSTER Lloyd k-means (codebook_gen::PER_CLUSTER,
    ivf_pq_types.hpp:36): one (n_codes, dsub) codebook per IVF list, trained
    on ALL sub-vectors of that list's residuals pooled across subspaces.

    resid_sub: (n_train, pq_dim, dsub); labels: (n_train,) list ids. The
    whole EM is segment reductions keyed by label·n_codes + code — one
    fused program, no per-cluster host loop."""
    n_train, pq_dim, dsub = resid_sub.shape
    sub = resid_sub.reshape(n_train * pq_dim, dsub)
    sub_label = jnp.repeat(labels.astype(jnp.int32), pq_dim)
    nseg = n_lists * n_codes

    # init: per (list, seed) slot, a random member sub-vector of that list
    # (segment-argmax of per-slot uniforms; only 8 seed rows are drawn —
    # round-3 review: an (n_codes, n·pq_dim) uniform was multi-GB)
    n_seed = min(n_codes, 8)
    u = jax.random.uniform(key, (n_seed, sub.shape[0]))

    def init_code(u_c):
        top = jax.ops.segment_max(u_c, sub_label, num_segments=n_lists)
        is_rep = u_c >= top[sub_label]
        rep = jax.ops.segment_min(
            jnp.where(is_rep, jnp.arange(sub.shape[0], dtype=jnp.int32),
                      sub.shape[0] - 1),
            sub_label, num_segments=n_lists)
        return sub[rep]                                   # (n_lists, dsub)

    cb0 = jnp.stack([init_code(u[c]) for c in range(n_seed)], axis=1)
    if n_codes > n_seed:  # jitter copies of the seeds: Lloyd separates them
        reps = -(-n_codes // n_seed)
        jit_key = jax.random.fold_in(key, 1)
        noise = jax.random.normal(jit_key, (n_lists, n_seed * reps, dsub)) * 0.05
        spread = jnp.std(sub) + 1e-6
        cb0 = (jnp.tile(cb0, (1, reps, 1)) + noise * spread)[:, :n_codes]

    # chunk the per-row assignment so the (chunk, s, n_codes) distance block
    # stays bounded (review: unchunked it was multi-GB at default sizes)
    chunk = max(256, min(n_train, 4_000_000 // max(pq_dim * n_codes, 1)))
    n_chunks = -(-n_train // chunk)
    pad = n_chunks * chunk - n_train

    def step(_, cb):
        rows_p = jnp.pad(resid_sub, ((0, pad), (0, 0), (0, 0)))
        lb_p = jnp.pad(labels, (0, pad))

        def one(args):
            rows, lb = args
            cb_l = cb[lb]                                  # (chunk, nc, d)
            d2 = (jnp.sum(cb_l * cb_l, axis=2)[:, None, :]
                  - 2.0 * jnp.einsum("nsd,ncd->nsc", rows, cb_l,
                                     preferred_element_type=jnp.float32))
            return jnp.argmin(d2, axis=2).astype(jnp.int32)

        code = lax.map(one, (rows_p.reshape(n_chunks, chunk, pq_dim, dsub),
                             lb_p.reshape(n_chunks, chunk)))
        code = code.reshape(-1, pq_dim)[:n_train]          # (n_train, s)
        seg = sub_label * n_codes + code.reshape(-1)
        sums = jax.ops.segment_sum(sub, seg, num_segments=nseg)
        cnts = jax.ops.segment_sum(jnp.ones(sub.shape[0]), seg,
                                   num_segments=nseg)
        new = sums / jnp.maximum(cnts, 1.0)[:, None]
        new = new.reshape(n_lists, n_codes, dsub)
        return jnp.where(cnts.reshape(n_lists, n_codes, 1) > 0, new, cb)

    return lax.fori_loop(0, n_iters, step, cb0)


def _encode_cluster(resid_rot, labels, codebooks, chunk: int = 8192):
    """Per-cluster encode: each row scores against ITS list's codebook."""
    n, pq_dim, dsub = resid_rot.shape
    cn = jnp.sum(codebooks * codebooks, axis=2)            # (L, c)

    def enc(args):
        rows, lb = args
        cb_l = codebooks[lb]                               # (chunk, c, d)
        ip = jnp.einsum("nsd,ncd->nsc", rows, cb_l,
                        preferred_element_type=jnp.float32)
        return jnp.argmin(cn[lb][:, None, :] - 2.0 * ip, axis=2).astype(jnp.uint8)

    if n <= chunk:
        return enc((resid_rot, labels))
    n_chunks = -(-n // chunk)
    pad = n_chunks * chunk - n
    rows_p = jnp.pad(resid_rot, ((0, pad), (0, 0), (0, 0)))
    lb_p = jnp.pad(labels, (0, pad))
    out = lax.map(enc, (rows_p.reshape(n_chunks, chunk, pq_dim, dsub),
                        lb_p.reshape(n_chunks, chunk)))
    return out.reshape(-1, pq_dim)[:n]


def _pack_lists(codes, row_ids, labels, n_lists: int, group: int = 0):
    if group <= 0:
        group = _packing.auto_group_size(codes.shape[0], n_lists, floor=128)
    return _packing.pack_lists(codes, row_ids, labels, n_lists, group,
                               pow2_chunks=group == 512)


# legacy private alias (pre-promotion call sites across the repo and old
# user code imported `_pad_rot` from here)
_pad_rot = linalg.pad_rot


@traced("ivf_pq::build")
def build(
    dataset,
    params: IvfPqParams = IvfPqParams(),
    res: Optional[Resources] = None,
) -> IvfPqIndex:
    """Train coarse centers, rotation, codebooks; encode and pack the lists
    (ivf_pq-inl.cuh:273 / detail/ivf_pq_build.cuh:1729)."""
    res = res or current_resources()
    dataset = jnp.asarray(dataset).astype(jnp.float32)
    n, dim = dataset.shape
    if params.n_lists > n:
        raise ValueError(f"n_lists={params.n_lists} > n_rows={n}")
    pq_dim = params.pq_dim or _auto_pq_dim(dim)
    if pq_dim > dim:
        raise ValueError(f"pq_dim={pq_dim} > dim={dim}")
    dsub = -(-dim // pq_dim)
    rot_dim = pq_dim * dsub
    n_codes = 1 << params.pq_bits

    work = dataset
    if params.metric == "cosine":
        work = work / jnp.maximum(jnp.linalg.norm(work, axis=1, keepdims=True), 1e-30)

    # --- coarse quantizer (ivf_pq_build.cuh:1828) --------------------------
    km_metric = "inner_product" if params.metric in ("cosine", "inner_product") else "sqeuclidean"
    km = kmeans_balanced.KMeansBalancedParams(
        n_iters=params.kmeans_n_iters, metric=km_metric, seed=params.seed
    )
    key = jax.random.key(params.seed)
    k_train, k_rot, k_cb = jax.random.split(key, 3)
    n_train = max(params.n_lists, int(n * params.kmeans_trainset_fraction))
    # phase spans (round-8): the @traced entry span parents these via the
    # tracing contextvar, so a Perfetto export shows WHERE inside a build
    # the time went (entry → phase → tile), not just that it ran
    with obs.record_span("ivf_pq::coarse_train"):
        if n_train < n:
            # with-replacement: duplicates are noise for k-means, and it
            # avoids the O(n log n) permutation program
            # choice(replace=False) compiles
            train_rows = jax.random.randint(k_train, (n_train,), 0, n)
            trainset = work[train_rows]
            centers = kmeans_balanced.fit(trainset, params.n_lists, km, res=res)
            labels = kmeans_balanced.predict(work, centers, km, res=res)
        else:
            trainset = work
            centers, labels = kmeans_balanced.fit_predict(work, params.n_lists, km, res=res)

    # --- rotation + codebooks (ivf_pq_build.cuh:119,:392) ------------------
    with obs.record_span("ivf_pq::codebook_train"):
        rotation = make_rotation_matrix(k_rot, rot_dim)
        train_labels = kmeans_balanced.predict(trainset, centers, km, res=res)
        resid = _pad_rot(trainset - centers[train_labels], rot_dim) @ rotation.T
        cb_rows = min(resid.shape[0], 65536)
        resid_cb = resid[:cb_rows].reshape(cb_rows, pq_dim, dsub)
        if params.codebook_kind == "cluster":
            codebooks = _train_codebooks_cluster(
                resid_cb, train_labels[:cb_rows], k_cb, n_codes,
                params.codebook_n_iters, params.n_lists)
        else:
            codebooks = _train_codebooks(
                resid_cb.transpose(1, 0, 2), k_cb, n_codes,
                params.codebook_n_iters)

    if obs.enabled():
        obs.add("ivf_pq.build.rows", n)
        obs.add("ivf_pq.build.lists", params.n_lists)

    group = params.group_size or _packing.auto_group_size(n, params.n_lists, floor=128)
    cap = params.list_size_cap
    if cap < 0:
        cap = _packing.auto_list_cap(n, params.n_lists, group)
    if cap:
        labels = _packing.spill_to_cap(work, centers, labels, km_metric, cap)

    # --- encode + pack, pq_bits-tight (ivf_pq_build.cuh:1319) --------------
    # residuals + encode in row chunks: one (n, rot_dim) fp32 residual
    # array is ~4 GB at 10M x 96 — materializing it whole next to `work`
    # OOM'd the 10M bench (round-4); chunking bounds the transient to the
    # workspace while `codes` (uint8) stays small
    enc_chunk = int(max(65536, res.workspace_bytes // max(rot_dim * 16, 1)))
    enc_attrs = ({"rows": int(n), "chunk": enc_chunk}
                 if obs.enabled() else None)
    with obs.record_span("ivf_pq::encode", attrs=enc_attrs):
        codes_parts = []
        for s in range(0, n, enc_chunk):
            e = min(s + enc_chunk, n)
            with obs.record_span("ivf_pq::encode_tile",
                                 attrs=({"rows": int(e - s)}
                                        if obs.enabled() else None)):
                wch = lax.slice_in_dim(work, s, e, axis=0)
                lch = lax.slice_in_dim(labels, s, e, axis=0)
                resid = _pad_rot(wch - centers[lch], rot_dim) @ rotation.T
                resid = resid.reshape(e - s, pq_dim, dsub)
                raw = (_encode_cluster(resid, lch, codebooks)
                       if params.codebook_kind == "cluster"
                       else _encode(resid, codebooks))
                codes_parts.append(pack_codes(raw, params.pq_bits))
        codes = (jnp.concatenate(codes_parts) if len(codes_parts) > 1
                 else codes_parts[0])
    with obs.record_span("ivf_pq::pack"):
        row_ids = jnp.arange(n, dtype=jnp.int32)
        list_codes, list_ids = _pack_lists(codes, row_ids, labels,
                                           params.n_lists, group)
        b_sum = _compute_b_sum(centers, rotation, codebooks, list_codes,
                               list_ids, params.metric, pq_dim,
                               params.pq_bits,
                               cluster=params.codebook_kind == "cluster")
    return IvfPqIndex(
        centers, rotation, codebooks, list_codes, list_ids, b_sum, None,
        params.metric, params.pq_bits, group,
        codebook_kind=params.codebook_kind, pq_dim_hint=pq_dim,
    )


# promoted to _packing (round 17: the ivf_bq streamed build shares them);
# the private aliases keep this module's long-standing names working
_chunk_ranks = _packing.chunk_ranks


@functools.partial(
    jax.jit,
    static_argnames=("pq_dim", "pq_bits", "cluster", "code_w"),
    donate_argnums=(0, 1),
)
def _scatter_chunk(list_codes, list_ids, chunk, labels, base, row_start,
                   centers, rotation, codebooks,
                   pq_dim, pq_bits, cluster, code_w):
    """One streamed-build chunk: encode + offset-scatter into the donated
    packed blocks (build_streaming pass 2). ``base`` is the per-list write
    offset accumulated over previous chunks; the in-chunk rank comes from
    one chunk-local sort, so no global position array ever exists."""
    m, dim = chunk.shape
    n_lists, mls = list_ids.shape
    dsub = codebooks.shape[-1]
    rot_dim = pq_dim * dsub
    safe = jnp.minimum(labels, n_lists - 1)
    resid = _pad_rot(chunk - centers[safe], rot_dim) @ rotation.T
    resid = resid.reshape(m, pq_dim, dsub)
    raw = (_encode_cluster(resid, safe, codebooks) if cluster
           else _encode(resid, codebooks))
    codes = pack_codes(raw, pq_bits)
    # chunk-local rank within each list; sentinel labels (== n_lists, the
    # diversion drop marker) and overflow past mls route to row mls, which
    # mode="drop" discards
    order, sorted_labels, rank_sorted = _chunk_ranks(labels, n_lists)
    safe_sl = jnp.minimum(sorted_labels, n_lists - 1)
    pos = base[safe_sl].astype(jnp.int32) + rank_sorted
    pos = jnp.where((sorted_labels < n_lists) & (pos < mls), pos, mls)
    list_codes = list_codes.at[safe_sl, pos].set(
        codes[order], mode="drop")
    ids = row_start + jnp.arange(m, dtype=jnp.int32)
    list_ids = list_ids.at[safe_sl, pos].set(
        ids[order], mode="drop")
    return list_codes, list_ids


_assign_top2 = _packing.assign_top2


@functools.partial(
    jax.jit,
    static_argnames=("pq_dim", "pq_bits", "cluster", "cache_dim"),
    donate_argnums=(0, 1, 2),
)
def _scatter_chunk_cache(cache, list_ids, b_sum, chunk, labels, base,
                         row_start, centers, rotation, codebooks, rc_t,
                         pq_dim, pq_bits, cluster, cache_dim):
    """Streamed-build chunk for ``store="cache"``: encode → reconstruct →
    int8-truncate to ``cache_dim`` rotated coords, then offset-scatter the
    cache + ids + per-entry b_sum into the donated blocks. The codes are
    transient — at 100M×96 keeping BOTH packed codes and the cache busts
    HBM, and truncating the cache is the quantize-harder decision
    (detail/ivf_pq_fp_8bit.cuh analog: precision traded for memory, exact
    refine absorbs it)."""
    m, dim = chunk.shape
    n_lists, mls = list_ids.shape
    dsub = codebooks.shape[-1]
    rot_dim = pq_dim * dsub
    safe = jnp.minimum(labels, n_lists - 1)
    resid = _pad_rot(chunk - centers[safe], rot_dim) @ rotation.T
    resid3 = resid.reshape(m, pq_dim, dsub)
    raw = (_encode_cluster(resid3, safe, codebooks) if cluster
           else _encode(resid3, codebooks))
    packed = pack_codes(raw, pq_bits)
    scale = jnp.maximum(jnp.max(jnp.abs(codebooks)), 1e-30) / 127.0
    # decode through GL pseudo-lists: one giant take over the whole chunk
    # is the gather shape class that faults the tunneled TPU runtime
    # (round-2 finding); 64 slices keep each take at the proven per-list
    # scale
    GL = 64
    mp = -(-m // GL) * GL
    packed_p = jnp.pad(packed, ((0, mp - m), (0, 0)))
    rec = _decode_lists_scaled(
        codebooks, packed_p.reshape(GL, mp // GL, packed.shape[-1]),
        scale, pq_dim, pq_bits, cluster)
    rot_dim_full = pq_dim * dsub
    rec_t = rec.reshape(mp, rot_dim_full)[:m, :cache_dim]
    rf = rec_t.astype(jnp.float32) * scale
    # truncated-space b_sum: 2⟨(Rc_l)[:cd], r̂_t⟩ + ‖r̂_t‖² (the scan's
    # −2⟨q_rot[:cd], r̂_t⟩ completes the cross term; ‖Rc‖² rides
    # _ragged_bias_pq, −2⟨q,c⟩ rides pair_const — both exact)
    b = (2.0 * jnp.einsum("md,md->m", rc_t[safe], rf,
                          preferred_element_type=jnp.float32)
         + jnp.einsum("md,md->m", rf, rf,
                      preferred_element_type=jnp.float32))
    order, sorted_labels, rank_sorted = _chunk_ranks(labels, n_lists)
    safe_sl = jnp.minimum(sorted_labels, n_lists - 1)
    pos = base[safe_sl].astype(jnp.int32) + rank_sorted
    pos = jnp.where((sorted_labels < n_lists) & (pos < mls), pos, mls)
    cache = cache.at[safe_sl, pos].set(rec_t[order], mode="drop")
    ids = row_start + jnp.arange(m, dtype=jnp.int32)
    list_ids = list_ids.at[safe_sl, pos].set(ids[order], mode="drop")
    b_sum = b_sum.at[safe_sl, pos].set(b[order], mode="drop")
    return cache, list_ids, b_sum


@traced("ivf_pq::build_streaming")
def build_streaming(
    chunk_fn,
    n: int,
    dim: int,
    params: IvfPqParams = IvfPqParams(),
    res: Optional[Resources] = None,
    chunk_rows: int = 0,
    train_rows: int = 0,
    store: str = "codes",
    cache_dim: int = 0,
) -> IvfPqIndex:
    """Out-of-HBM build: the dataset visits the device one chunk at a time
    (the 100M-row single-chip configuration, BASELINE DEEP-100M row).

    ``chunk_fn(start, end) -> (end-start, dim) array`` supplies rows — a
    file reader (bench/io.py readers), a generator, or a host array slice.
    It is called once per chunk per pass (twice total), so it must be
    deterministic.

    Differences from :func:`build`, all forced by the memory budget:

    * quantizers train on ``train_rows`` sampled rows (default ≤2M) — the
      reference trains on a host-side subsample for the same reason
      (ivf_pq_build.cuh:1729);
    * pass 1 streams assignments (labels are kept, ~4 B/row); pass 2
      encodes each chunk and scatters at precomputed per-list offsets into
      DONATED blocks — peak HBM is the index + one chunk, never the raw
      matrix (vs extend()'s whole-index repack per call, O(n²) over a
      chunk stream);
    * the list cap (``params.list_size_cap``) is enforced by ONE-PASS
      capacity diversion: a row whose nearest list is full goes to its
      second-nearest (:func:`_assign_top2` — the streaming analog of
      _packing.spill_to_cap's first alternative round); rows whose second
      choice is also full are DROPPED and counted
      (``index._streaming_dropped``) — at the auto cap this is empty;
    * ``store="codes"`` keeps packed codes (search via ``backend="pallas"``
      or lazy cache decode); ``store="cache"`` keeps ONLY the int8
      strip-scan cache, truncated to the first ``cache_dim`` rotated
      coordinates — the quantize-harder memory decision
      (detail/ivf_pq_fp_8bit.cuh analog) that makes 100M×96 fit one 16 GB
      chip next to its own transients; such an index searches at full
      strip speed but cannot extend() or re-derive codes.
    """
    import numpy as np

    res = res or current_resources()
    if params.metric == "cosine":
        raise ValueError("build_streaming: cosine needs normalized chunks; "
                         "normalize inside chunk_fn and use inner_product")
    if store not in ("codes", "cache"):
        raise ValueError(f"unknown store mode {store!r}")
    if store == "cache" and params.codebook_kind == "cluster":
        raise ValueError(
            "store='cache' supports subspace codebooks only (the chunked "
            "decode regroups rows across lists, which a per-list codebook "
            "cannot follow); use store='codes' for PER_CLUSTER")
    pq_dim = params.pq_dim or _auto_pq_dim(dim)
    dsub = -(-dim // pq_dim)
    rot_dim = pq_dim * dsub
    cd = int(cache_dim) or rot_dim
    if not 0 < cd <= rot_dim:
        raise ValueError(f"cache_dim={cd} out of range (1..{rot_dim})")
    n_codes = 1 << params.pq_bits
    cluster = params.codebook_kind == "cluster"
    km_metric = ("inner_product" if params.metric == "inner_product"
                 else "sqeuclidean")
    km = kmeans_balanced.KMeansBalancedParams(
        n_iters=params.kmeans_n_iters, metric=km_metric, seed=params.seed)
    chunk = int(chunk_rows) or int(
        max(262_144, min(n, res.workspace_bytes // max(dim * 12, 1))))
    chunk = min(chunk, n)
    starts = list(range(0, n, chunk))
    group = params.group_size or _packing.auto_group_size(
        n, params.n_lists, floor=128)
    cap = params.list_size_cap
    if cap < 0:
        cap = _packing.auto_list_cap(n, params.n_lists, group)

    from raft_tpu.core.interruptible import check_interrupt

    # --- quantizers on a strided sample ------------------------------------
    t_rows = int(train_rows) or int(min(2_000_000, max(
        params.n_lists * 32, n * params.kmeans_trainset_fraction)))
    t_rows = min(t_rows, n)
    per = max(1, t_rows // len(starts))
    train_parts = [jnp.asarray(chunk_fn(s, min(s + per, n)), jnp.float32)
                   for s in starts]
    trainset = (jnp.concatenate(train_parts) if len(train_parts) > 1
                else train_parts[0])
    del train_parts
    centers = kmeans_balanced.fit(trainset, params.n_lists, km, res=res)
    key = jax.random.key(params.seed)
    _, k_rot, k_cb = jax.random.split(key, 3)
    rotation = make_rotation_matrix(k_rot, rot_dim)
    train_labels = kmeans_balanced.predict(trainset, centers, km, res=res)
    cb_rows = min(trainset.shape[0], 65536)
    resid = (_pad_rot(trainset[:cb_rows] - centers[train_labels[:cb_rows]],
                      rot_dim) @ rotation.T).reshape(cb_rows, pq_dim, dsub)
    if cluster:
        codebooks = _train_codebooks_cluster(
            resid, train_labels[:cb_rows], k_cb, n_codes,
            params.codebook_n_iters, params.n_lists)
    else:
        codebooks = _train_codebooks(resid.transpose(1, 0, 2), k_cb,
                                     n_codes, params.codebook_n_iters)
    del trainset, train_labels, resid

    # --- pass 1: streamed assignment (+ capacity diversion under a cap) ----
    n_lists = params.n_lists
    run = np.zeros(n_lists, np.int64)
    counts_np = np.zeros((len(starts), n_lists), np.int64)
    labels_chunks = []
    dropped = 0
    for ci, s in enumerate(starts):
        check_interrupt()
        e = min(s + chunk, n)
        rows = jnp.asarray(chunk_fn(s, e), jnp.float32)
        if cap:
            l1, l2 = _assign_top2(rows, centers, metric=km_metric)
            labels = _divert_to_cap(l1, l2, jnp.asarray(run, jnp.int32),
                                    jnp.int32(cap), n_lists)
        else:
            labels = kmeans_balanced.predict(rows, centers, km, res=res)
        labels_chunks.append(labels)
        c = np.asarray(jnp.bincount(jnp.minimum(labels, n_lists),
                                    length=n_lists + 1))
        counts_np[ci] = c[:n_lists]
        dropped += int(c[n_lists])
        run += c[:n_lists]
        del rows
    totals = counts_np.sum(axis=0)
    mls = int(max(group, -(-int(totals.max()) // group) * group))
    if group == 512:  # strip backend block-divisibility (pow2 chunks)
        mls = 512 * (1 << (mls // 512 - 1).bit_length())
    base_np = np.cumsum(counts_np, axis=0) - counts_np  # per-chunk offsets
    if dropped:
        from raft_tpu.core.logger import get_logger

        get_logger().warning(
            "build_streaming: %d row(s) overflowed both their nearest and "
            "second-nearest capped lists and were dropped (cap=%d); raise "
            "list_size_cap or n_lists.", dropped, cap)

    # --- pass 2: encode + offset-scatter into donated blocks ---------------
    list_ids = jnp.full((n_lists, mls), -1, jnp.int32)
    if store == "cache":
        cache = jnp.zeros((n_lists, mls, cd), jnp.int8)
        b_sum = jnp.full((n_lists, mls), jnp.inf, jnp.float32)
        rc_t = ((_pad_rot(centers, rot_dim) @ rotation.T)[:, :cd])
        for ci, s in enumerate(starts):
            check_interrupt()
            e = min(s + chunk, n)
            rows = jnp.asarray(chunk_fn(s, e), jnp.float32)
            cache, list_ids, b_sum = _scatter_chunk_cache(
                cache, list_ids, b_sum, rows, labels_chunks[ci],
                jnp.asarray(base_np[ci], jnp.int32), jnp.int32(s),
                centers, rotation, codebooks, rc_t,
                pq_dim, params.pq_bits, cluster, cd)
            del rows
        scale = jnp.maximum(jnp.max(jnp.abs(codebooks)), 1e-30) / 127.0
        if params.metric in ("inner_product",):
            b_sum = jnp.where(list_ids >= 0, 0.0, jnp.inf)
        out = IvfPqIndex(
            centers, rotation, codebooks,
            jnp.zeros((n_lists, mls, 0), jnp.uint8), list_ids, b_sum,
            cache, params.metric, params.pq_bits, group,
            decoded_scale=scale, codebook_kind=params.codebook_kind,
            pq_dim_hint=pq_dim)
    else:
        code_w = packed_width(pq_dim, params.pq_bits)
        list_codes = jnp.zeros((n_lists, mls, code_w), jnp.uint8)
        for ci, s in enumerate(starts):
            check_interrupt()
            e = min(s + chunk, n)
            rows = jnp.asarray(chunk_fn(s, e), jnp.float32)
            list_codes, list_ids = _scatter_chunk(
                list_codes, list_ids, rows, labels_chunks[ci],
                jnp.asarray(base_np[ci], jnp.int32), jnp.int32(s),
                centers, rotation, codebooks,
                pq_dim, params.pq_bits, cluster, code_w)
            del rows
        b_sum = _compute_b_sum(centers, rotation, codebooks, list_codes,
                               list_ids, params.metric, pq_dim,
                               params.pq_bits, cluster=cluster)
        out = IvfPqIndex(
            centers, rotation, codebooks, list_codes, list_ids, b_sum,
            None, params.metric, params.pq_bits, group,
            codebook_kind=params.codebook_kind, pq_dim_hint=pq_dim)
    out._streaming_dropped = dropped
    return out


_divert_to_cap = _packing.divert_to_cap


@functools.partial(jax.jit, static_argnames=("pq_dim", "pq_bits", "cluster"))
def _decode_lists(codebooks, list_codes, pq_dim=None, pq_bits: int = 8,
                  cluster: bool = False):
    """int8-quantized RESIDUAL reconstruction cb[codes] per entry, in
    rotated space — the strip-scan cache at rot_dim bytes/entry (the
    quantized-reconstruction analog of the reference's fp8-compressed LUT,
    detail/ivf_pq_fp_8bit.cuh: precision traded for bandwidth, re-ranked by
    refine; the residual matmul is 2·rot_dim FLOP/entry where the one-hot
    LUT scan pays 2·pq_dim·n_codes for the same ranking).

    Residual-only (round 3): the −2⟨q, R·c_l⟩ half of the cross term is
    constant within a (query, probe) pair, so the merge adds it exactly
    AFTER extraction (strip_search's pair_const) — the cache only carries
    codebook entries, whose max|·| is a far tighter int8 scale than the
    full reconstruction's. The scale is max|codebooks|/127 — exact, data
    independent, and identical on every shard for free.

    Returns (cache int8 (n_lists, m, rot_dim), scale 0-d fp32)."""
    scale = jnp.maximum(jnp.max(jnp.abs(codebooks)), 1e-30) / 127.0
    return _decode_lists_scaled(codebooks, list_codes, scale, pq_dim,
                                pq_bits, cluster), scale


def _codes_view(list_codes, pq_dim, pq_bits):
    """Per-list unpacked (m, pq_dim) codes from possibly bit-packed rows."""
    if pq_dim is None or list_codes.shape[-1] == pq_dim:
        return list_codes
    return unpack_codes(list_codes, pq_dim, pq_bits)


def _decode_lists_scaled(codebooks, list_codes, scale, pq_dim=None,
                         pq_bits: int = 8, cluster: bool = False):
    """int8 residual cache at a given dequant scale. ``cluster`` selects the
    PER_CLUSTER codebook layout (one codebook per list)."""
    n_lists, max_size = list_codes.shape[0], list_codes.shape[1]
    n_codes, dsub = codebooks.shape[1], codebooks.shape[2]
    if pq_dim is None:
        pq_dim = list_codes.shape[-1]
    rot_dim = pq_dim * dsub
    cb_q = jnp.clip(jnp.round(codebooks / scale), -127, 127).astype(jnp.int8)

    if cluster:
        def quant_one(args):
            cb_l, codes_l = args  # (c, d), (m, ·)
            codes_l = _codes_view(codes_l, pq_dim, pq_bits)
            resid = jnp.take(cb_l, codes_l.astype(jnp.int32), axis=0)
            return resid.reshape(max_size, rot_dim)

        return lax.map(quant_one, (cb_q, list_codes))

    cb_flat = cb_q.reshape(pq_dim * n_codes, dsub)
    s_off = (jnp.arange(pq_dim, dtype=jnp.int32) * n_codes)[None, :]

    def quant_one(codes_l):
        codes_l = _codes_view(codes_l, pq_dim, pq_bits)
        resid = jnp.take(cb_flat, codes_l.astype(jnp.int32) + s_off, axis=0)
        return resid.reshape(max_size, rot_dim)

    return lax.map(quant_one, list_codes)


def _compute_b_sum(centers, rotation, codebooks, list_codes, list_ids, metric,
                   pq_dim=None, pq_bits: int = 8, cluster: bool = False):
    """List-side LUT half, baked per entry: Σ_s (2·(Rc_l)_s·cb[s,code] +
    |cb[s,code]|²) for L2; zeros for inner-product metrics (module docstring
    derivation). Padding entries get +inf so the scan output self-masks."""
    n_lists, max_size = list_codes.shape[0], list_codes.shape[1]
    if pq_dim is None:
        pq_dim = list_codes.shape[-1]
    pad_inf = jnp.where(list_ids >= 0, 0.0, jnp.inf).astype(jnp.float32)
    if metric in ("inner_product", "cosine"):
        return pad_inf
    dsub = codebooks.shape[2]
    n_codes = codebooks.shape[1]
    rot_dim = pq_dim * dsub
    rc = (_pad_rot(centers, rot_dim) @ rotation.T).reshape(n_lists, pq_dim, dsub)
    # B[l, s, c] = 2 (Rc_l)_s · cb[s or l, c] + |cb|²
    if cluster:
        B = 2.0 * jnp.einsum("lsd,lcd->lsc", rc, codebooks,
                             preferred_element_type=jnp.float32)
        B = B + jnp.sum(codebooks * codebooks, axis=2)[:, None, :]
    else:
        B = 2.0 * jnp.einsum("lsd,scd->lsc", rc, codebooks,
                             preferred_element_type=jnp.float32)
        B = B + jnp.sum(codebooks * codebooks, axis=2)[None]
    # per-list flat gather (take from a 1-d table per list — avoids the
    # (l, m, s, n_codes) broadcast a take_along_axis would materialize)
    s_off = (jnp.arange(pq_dim, dtype=jnp.int32) * n_codes)[None, :]

    def one_list(args):
        B_l, codes_l = args  # (s, c), (m, ·)
        codes_l = _codes_view(codes_l, pq_dim, pq_bits)
        flat_idx = codes_l.astype(jnp.int32) + s_off
        return jnp.sum(jnp.take(B_l.reshape(-1), flat_idx, axis=0), axis=1)

    return lax.map(one_list, (B, list_codes)) + pad_inf


@traced("ivf_pq::extend")
def extend(index: IvfPqIndex, new_vectors, new_ids=None, res: Optional[Resources] = None) -> IvfPqIndex:
    """Encode new vectors with the existing quantizers and repack
    (ivf_pq extend analog)."""
    res = res or current_resources()
    if index.list_codes.shape[-1] == 0:
        raise ValueError(
            "cache-only streamed index (build_streaming store='cache') "
            "keeps no codes and cannot extend(); rebuild with "
            "store='codes'")
    new_vectors = jnp.asarray(new_vectors).astype(jnp.float32)
    if new_vectors.shape[1] != index.dim:
        raise ValueError(f"dim mismatch: {new_vectors.shape[1]} != {index.dim}")
    if index.metric == "cosine":
        new_vectors = new_vectors / jnp.maximum(
            jnp.linalg.norm(new_vectors, axis=1, keepdims=True), 1e-30
        )
    km_metric = "inner_product" if index.metric in ("cosine", "inner_product") else "sqeuclidean"
    labels = kmeans_balanced.predict(
        new_vectors, index.centers, kmeans_balanced.KMeansBalancedParams(metric=km_metric), res=res
    )
    # persisted granule; legacy indexes (group_size 0) fall back to inference
    group = index.group_size or (512 if index.max_list_size % 512 == 0 else 128)
    total = index.size + int(new_vectors.shape[0])
    cap = _packing.auto_list_cap(total, index.n_lists, group)
    # spill BEFORE encoding: residuals are taken against the assigned center
    labels = _packing.spill_to_cap(
        new_vectors, index.centers, labels, km_metric, cap,
        base_counts=index.list_sizes(),
    )
    dsub = index.codebooks.shape[2]
    cluster = index.codebook_kind == "cluster"
    resid = _pad_rot(new_vectors - index.centers[labels], index.rot_dim) @ index.rotation.T
    resid3 = resid.reshape(new_vectors.shape[0], index.pq_dim, dsub)
    if cluster:
        codes = _encode_cluster(resid3, labels, index.codebooks)
    else:
        codes = _encode(resid3, index.codebooks)
    codes = pack_codes(codes, index.pq_bits)

    old_valid = index.list_ids.reshape(-1) >= 0
    old_codes = index.list_codes.reshape(-1, index.list_codes.shape[-1])[old_valid]
    if old_codes.shape[-1] != packed_width(index.pq_dim, index.pq_bits):
        # legacy pre-packing index (pq_bits < 8 stored one byte/subdim):
        # repack so widths match the newly encoded rows
        old_codes = pack_codes(old_codes, index.pq_bits)
    old_ids = index.list_ids.reshape(-1)[old_valid]
    old_labels = jnp.repeat(
        jnp.arange(index.n_lists, dtype=jnp.int32), index.max_list_size
    )[old_valid]
    if new_ids is None:
        start = int(jnp.max(old_ids) + 1) if old_ids.size else 0
        new_ids = jnp.arange(start, start + new_vectors.shape[0], dtype=jnp.int32)
    else:
        new_ids = jnp.asarray(new_ids, jnp.int32)

    all_codes = jnp.concatenate([old_codes, codes])
    all_ids = jnp.concatenate([old_ids, new_ids])
    all_labels = jnp.concatenate([old_labels, labels])
    list_codes, list_ids = _pack_lists(all_codes, all_ids, all_labels, index.n_lists, group)
    b_sum = _compute_b_sum(
        index.centers, index.rotation, index.codebooks, list_codes, list_ids,
        index.metric, index.pq_dim, index.pq_bits, cluster=cluster,
    )
    return IvfPqIndex(
        index.centers, index.rotation, index.codebooks, list_codes, list_ids,
        b_sum, None, index.metric, index.pq_bits, group,
        codebook_kind=index.codebook_kind, pq_dim_hint=index.pq_dim,
    )


# ---------------------------------------------------------------------------
# Search
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("l2",))
def _ragged_bias_pq(b_sum, centers, rotation, list_ids, filter, l2: bool):
    """Per-entry bias for the decoded scan: ‖x̂‖² = ‖R·c_l‖² + b_sum for L2
    (b_sum already carries +inf at padding), 0/+inf for ip/cosine; filtered
    entries get +inf."""
    if l2:
        rot_dim = rotation.shape[0]
        rc2 = dist_mod.sqnorm(_pad_rot(centers, rot_dim) @ rotation.T)
        bias = rc2[:, None] + b_sum
    else:
        bias = b_sum
    return _filtering.apply_filter_bias(bias, list_ids, filter)


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "select_algo",
                     "compute_dtype", "l2", "classes", "class_counts",
                     "q_tile", "interpret"),
)
def _ragged_fused_pq(queries, centers, rotation, b_sum, list_ids, decoded,
                     decoded_scale, filter, cls_ord,
                     k, n_probes, metric, select_algo, compute_dtype, l2,
                     classes, class_counts, q_tile, interpret):
    """The whole PQ strip search as ONE dispatch (round-4; see ivf_flat.
    _ragged_fused): prep + device planning + int8 strip kernel + finalize,
    zero host syncs. The in-kernel tournament top-k is allowed
    (approx_ok=True): this path over-fetches and exact-re-ranks via
    neighbors/refine, which absorbs its ~1e-4/row bin-collision loss."""
    from raft_tpu.ops.strip_scan import strip_search_traced

    # ledger registration for the TPU-default backend too (trace time
    # only): a retrace on the platform of record must not be invisible
    obs_compile.trace_event(
        "ivf_pq.search_ragged", queries=queries, centers=centers,
        rotation=rotation, b_sum=b_sum, list_ids=list_ids, decoded=decoded,
        decoded_scale=decoded_scale, filter=filter, cls_ord=cls_ord,
        static={"k": k, "n_probes": n_probes, "metric": metric,
                "select_algo": select_algo, "compute_dtype": compute_dtype,
                "l2": l2, "classes": classes, "class_counts": class_counts,
                "q_tile": q_tile, "interpret": interpret})

    # packed coarse select only while its perturbation bound stays tight
    # (2^-(23-ceil(log2 n_lists)) ≤ 5e-4 at 4096 lists; ADVICE r4 medium —
    # see ivf_flat._ragged_fused)
    sa = ("packed" if select_algo == "exact" and not interpret
          and centers.shape[0] <= 4096 else select_algo)
    probes, qr_scaled, bias, pair_const = _pq_search_prep(
        queries, centers, rotation, b_sum, list_ids, decoded_scale,
        filter, n_probes, metric, sa, compute_dtype, l2,
    )
    # truncated cache (build_streaming store="cache", cache_dim < rot_dim):
    # the cache keeps only the leading rotated coords, so the query operand
    # drops the same tail — b_sum was built in the truncated space and the
    # center terms (‖Rc‖², −2⟨q,c⟩) stay exact
    if decoded.shape[-1] < qr_scaled.shape[-1]:
        qr_scaled = qr_scaled[:, :decoded.shape[-1]]
    vals, ids = strip_search_traced(
        qr_scaled, probes, decoded, bias, list_ids, cls_ord,
        classes, class_counts, int(k), int(k), -2.0 if l2 else -1.0,
        q_tile, interpret, pair_const=pair_const, approx_ok=True,
    )
    from raft_tpu.neighbors.ivf_flat import _finalize_ragged

    # shared fused finalizer: same score algebra — ‖Rq‖² == ‖q‖²
    # (orthogonal rotation; padding adds nothing), and cosine/ip scan
    # values use the same alpha=-1 convention
    return _finalize_ragged(vals, ids, queries, metric)


def _search_ragged_pq(index, queries, k, n_probes, filter, select_algo, res):
    """int8 residual-cache strip scan (ops/strip_scan.py): same ranking as
    the LUT formulation, at 2·rot_dim MXU FLOPs and rot_dim HBM bytes per
    probed entry instead of 2·pq_dim·n_codes FLOPs. The dequant scale folds
    into the query operand; the exact −2⟨q, R·c_l⟩ pair term rides the
    merge's pair_const (see _decode_lists)."""
    from raft_tpu.neighbors.ivf_flat import _ragged_plan_static

    if index.decoded is None:
        # lazy decode-cache fill, kept on the index instance
        index.decoded, index.decoded_scale = _decode_lists(
            index.codebooks, index.list_codes, pq_dim=index.pq_dim,
            pq_bits=index.pq_bits, cluster=index.codebook_kind == "cluster",
        )
    l2 = index.metric in ("sqeuclidean", "euclidean")
    # plan with the dim the kernel actually scans: a truncated streamed
    # cache (store="cache", cache_dim < rot_dim) narrows the fetch classes
    classes, class_counts, cls_ord, q_tile = _ragged_plan_static(
        index, n_probes, k, res, int(index.decoded.shape[-1]))
    return _ragged_fused_pq(
        queries, index.centers, index.rotation, index.b_sum, index.list_ids,
        index.decoded, index.decoded_scale, filter, cls_ord,
        int(k), n_probes, index.metric, select_algo, res.compute_dtype, l2,
        classes, class_counts, min(q_tile, queries.shape[0]),
        jax.default_backend() != "tpu",
    )


def _pq_probe_prep(queries, centers, rotation, n_probes, select_algo, l2,
                   rotation_kind: str = "dense"):
    """Probe selection + query rotation + the exact per-pair center term —
    THE one copy of the op sequence both the packed strip path and the
    paged Pallas path consume (bitwise parity between them is the paged
    plane's acceptance contract, so this math must not fork).
    ``rotation_kind`` selects the apply (ops/linalg.rotate_rows): the
    dense gemm, or the SRHT butterfly ivf_bq's Hadamard indexes carry."""
    ip_c = dist_mod.matmul_t(queries, centers, None, "highest")
    if l2:
        # expanded L2 from the single gemm (review: _expanded_distance would
        # recompute the same q×n_lists inner products)
        coarse = (dist_mod.sqnorm(queries)[:, None]
                  + dist_mod.sqnorm(centers)[None, :] - 2.0 * ip_c)
    else:
        coarse = -ip_c
    _, probes = select_k(coarse, n_probes, select_min=True, algo=select_algo)
    qr = linalg.rotate_rows(queries, rotation, rotation_kind)
    alpha = -2.0 if l2 else -1.0
    pair_const = alpha * jnp.take_along_axis(ip_c, probes, axis=1)
    return probes, qr, pair_const


@functools.partial(
    jax.jit,
    static_argnames=("n_probes", "metric", "select_algo", "compute_dtype",
                     "l2"),
)
def _pq_search_prep(queries, centers, rotation, b_sum, list_ids,
                    decoded_scale, filter, n_probes, metric, select_algo,
                    compute_dtype, l2):
    probes, qr, pair_const = _pq_probe_prep(
        queries, centers, rotation, n_probes, select_algo, l2)
    bias = _ragged_bias_pq(b_sum, centers, rotation, list_ids, filter, l2)
    return probes, qr * decoded_scale, bias, pair_const


def _query_luts(queries, rotation, codebooks, metric, lut_dtype):
    """Per-query LUT A (q, pq_dim, n_codes): the query-only half of the scan
    (module docstring). One einsum — rides the MXU."""
    q = queries.shape[0]
    pq_dim, n_codes, dsub = codebooks.shape
    rq = (_pad_rot(queries, pq_dim * dsub) @ rotation.T).reshape(q, pq_dim, dsub)
    A = jnp.einsum("qsd,scd->qsc", rq, codebooks, preferred_element_type=jnp.float32)
    if metric in ("sqeuclidean", "euclidean"):
        A = -2.0 * A
    else:  # inner product family: score = coarse_ip + Σ (Rq)·cb; negate → min
        A = -A
    return A.astype(lut_dtype)


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "q_tile", "select_algo",
                     "compute_dtype", "pq_dim", "pq_bits", "cluster"),
)
def _search_impl_jnp(
    queries, centers, rotation, codebooks, list_codes, list_ids, b_sum, filter,
    k, n_probes, metric, q_tile, select_algo, compute_dtype,
    pq_dim, pq_bits, cluster,
):
    """Gather-backend search: stage-1 coarse gemm + per-query LUT + code
    lookup via take_along_axis, tiled over queries."""
    # compile-ledger registration: runs at trace time only (obs/compile.py)
    obs_compile.trace_event(
        "ivf_pq.search", queries=queries, centers=centers,
        rotation=rotation, codebooks=codebooks, list_codes=list_codes,
        list_ids=list_ids, b_sum=b_sum, filter=filter,
        static={"k": k, "n_probes": n_probes, "metric": metric,
                "q_tile": q_tile, "select_algo": select_algo,
                "compute_dtype": compute_dtype, "pq_dim": pq_dim,
                "pq_bits": pq_bits, "cluster": cluster})
    q, dim = queries.shape
    n_lists, max_size = list_codes.shape[0], list_codes.shape[1]
    l2 = metric in ("sqeuclidean", "euclidean")

    # stage 1: coarse distances; keep probed values (they're the d² constant)
    if l2:
        coarse = dist_mod._expanded_distance(
            queries, centers, "sqeuclidean", compute_dtype, "highest"
        )
    else:
        coarse = -dist_mod.matmul_t(queries, centers, compute_dtype, "highest")
    coarse_vals, probes = select_k(coarse, n_probes, select_min=True, algo=select_algo)

    n_codes = codebooks.shape[1]
    dsub = codebooks.shape[2]
    if not cluster:
        luts = _query_luts(queries, rotation, codebooks, metric, jnp.float32)
        luts = luts.reshape(q, -1)  # (q, s*nc) flat per-query tables
    else:
        # per-cluster codebooks: the LUT varies by list, so it is built per
        # probed pair inside the tile scan; precompute rotated queries here
        luts = (_pad_rot(queries, pq_dim * dsub) @ rotation.T).reshape(
            q, pq_dim, dsub)

    s_off = (jnp.arange(pq_dim, dtype=jnp.int32) * n_codes)[None, None, :]

    def scan_tile(args):
        q_lut, probe_blk, cvals_blk = args  # (qt, ·), (qt, p), (qt, p)
        codes = _codes_view(list_codes[probe_blk], pq_dim, pq_bits) \
            .astype(jnp.int32)                           # (qt, p, m, s)
        ids = list_ids[probe_blk]  # (qt, p, m)
        if cluster:
            # per-pair LUT A[q, p, s, c] = sign·⟨(Rq)_s, cb_probe[c]⟩, then a
            # doubly-vmapped flat-table take (no broadcast materialization)
            cb_p = codebooks[probe_blk]                  # (qt, p, c, d)
            A = jnp.einsum("qsd,qpcd->qpsc", q_lut, cb_p,
                           preferred_element_type=jnp.float32)
            A = ((-2.0 if l2 else -1.0) * A).reshape(
                codes.shape[0], codes.shape[1], pq_dim * n_codes)
            flat_idx = codes + s_off[None]               # (qt, p, m, s)
            picked = jax.vmap(jax.vmap(
                lambda t, i: jnp.take(t, i, axis=0)))(A, flat_idx)
        else:
            # LUT lookup: out[q,p,m] = Σ_s q_lut[q, s*nc + codes[q,p,m,s]]
            # (per-query 1-d table take under vmap — no broadcast
            # materialization)
            flat_idx = codes + s_off[None]
            picked = jax.vmap(lambda lut, idx: jnp.take(lut, idx, axis=0))(q_lut, flat_idx)
        d = jnp.sum(picked, axis=3) + b_sum[probe_blk] + cvals_blk[:, :, None]
        if l2:
            d = jnp.maximum(d, 0.0)
            if metric == "euclidean":
                d = jnp.sqrt(d)
        flat_ids = ids.reshape(ids.shape[0], -1)
        d = d.reshape(flat_ids.shape)
        valid = flat_ids >= 0
        if filter is not None:
            valid = valid & filter.test(flat_ids)
        d = jnp.where(valid, d, jnp.inf)
        vals, sel = select_k(d, k, select_min=True, algo=select_algo)
        out_ids = jnp.where(jnp.isinf(vals), -1, jnp.take_along_axis(flat_ids, sel, axis=1))
        return vals, out_ids

    if q_tile >= q:
        vals, ids = scan_tile((luts, probes, coarse_vals))
    else:
        n_tiles = -(-q // q_tile)
        pad = n_tiles * q_tile - q
        lp = jnp.pad(luts, ((0, pad),) + ((0, 0),) * (luts.ndim - 1))
        pp = jnp.pad(probes, ((0, pad), (0, 0)))
        cp = jnp.pad(coarse_vals, ((0, pad), (0, 0)))
        vals, ids = lax.map(
            scan_tile,
            (
                lp.reshape((n_tiles, q_tile) + luts.shape[1:]),
                pp.reshape(n_tiles, q_tile, n_probes),
                cp.reshape(n_tiles, q_tile, n_probes),
            ),
        )
        vals = vals.reshape(-1, k)[:q]
        ids = ids.reshape(-1, k)[:q]
    if not l2:
        vals = -vals  # back to raw inner product (bigger = closer)
    return vals, ids


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "n_probes", "metric", "q_tile", "qpl_cap", "select_algo",
        "compute_dtype", "interpret", "pq_dim", "pq_bits",
    ),
)
def _search_impl_pallas(
    queries, centers, rotation, codebooks, list_codes, list_ids, b_sum, filter,
    k, n_probes, metric, q_tile, qpl_cap, select_algo, compute_dtype, interpret,
    pq_dim=None, pq_bits=8,
):
    """Pallas-backend search: list-centric scan kernel (ops/pq_scan.py).
    Subspace codebooks only (the kernel's LUT is per query, not per list)."""
    # ledger registration (trace time only): the qpl_cap escalation retry
    # DELIBERATELY retraces — the ledger attributes it to static.qpl_cap
    obs_compile.trace_event(
        "ivf_pq.search_pallas", queries=queries, centers=centers,
        rotation=rotation, codebooks=codebooks, list_codes=list_codes,
        list_ids=list_ids, b_sum=b_sum, filter=filter,
        static={"k": k, "n_probes": n_probes, "metric": metric,
                "q_tile": q_tile, "qpl_cap": qpl_cap,
                "select_algo": select_algo, "compute_dtype": compute_dtype,
                "interpret": interpret, "pq_dim": pq_dim,
                "pq_bits": pq_bits})
    q, dim = queries.shape
    n_lists, max_size = list_codes.shape[0], list_codes.shape[1]
    if pq_dim is None:
        pq_dim = list_codes.shape[-1]
    n_codes = codebooks.shape[1]
    l2 = metric in ("sqeuclidean", "euclidean")

    if l2:
        coarse = dist_mod._expanded_distance(
            queries, centers, "sqeuclidean", compute_dtype, "highest"
        )
    else:
        coarse = -dist_mod.matmul_t(queries, centers, compute_dtype, "highest")
    coarse_vals, probes = select_k(coarse, n_probes, select_min=True, algo=select_algo)

    luts = _query_luts(queries, rotation, codebooks, metric, jnp.bfloat16)
    luts = luts.reshape(q, -1)  # (q, f)
    codes_t = jnp.transpose(
        _codes_view(list_codes, pq_dim, pq_bits), (0, 2, 1)
    )  # (L, s, m), list dim minor

    def scan_tile(args):
        luts_t, probe_blk, cvals_blk, qmask = args  # (qt, f), (qt, p), (qt, p), (qt,)
        qt = probe_blk.shape[0]
        qids, slot = group_probed_pairs(probe_blk, n_lists, qpl_cap)
        # count real (query, probe) pairs beyond the per-list cap (ADVICE.md:
        # silent drops degrade recall under probe skew; surfaced to search()
        # which retries with a larger cap or falls back to the gather path)
        n_dropped = jnp.sum((slot < 0) & qmask[:, None])
        luts_g = jnp.where(
            (qids >= 0)[:, :, None], luts_t[jnp.maximum(qids, 0)], jnp.bfloat16(0)
        )
        # kernel output already includes b_sum and +inf at padding entries
        grouped = pq_scan(luts_g, codes_t, b_sum, n_codes, interpret)  # (L, qpl, m)
        scores = grouped[probe_blk, jnp.maximum(slot, 0)]  # (qt, p, m)
        # dropped pairs (slot -1) and the coarse constant in one fused pass
        d = scores + jnp.where(slot >= 0, cvals_blk, jnp.inf)[:, :, None]
        d = d.reshape(qt, -1)
        if filter is not None:
            ids_full = list_ids[probe_blk].reshape(qt, -1)
            d = jnp.where(filter.test(ids_full), d, jnp.inf)
        vals, sel = select_k(d, k, select_min=True, algo=select_algo)
        # map only the k winners: flat pos -> (probe slot, in-list pos) -> id
        win_list = jnp.take_along_axis(probe_blk, sel // max_size, axis=1)
        out_ids = list_ids[win_list, sel % max_size]
        out_ids = jnp.where(jnp.isinf(vals), -1, out_ids)
        if l2:
            vals = jnp.maximum(vals, 0.0)
            if metric == "euclidean":
                vals = jnp.sqrt(vals)
        return vals, out_ids, n_dropped

    if q_tile >= q:
        vals, ids, dropped = scan_tile(
            (luts, probes, coarse_vals, jnp.ones((q,), jnp.bool_))
        )
    else:
        n_tiles = -(-q // q_tile)
        pad = n_tiles * q_tile - q
        lp = jnp.pad(luts, ((0, pad), (0, 0)))
        pp = jnp.pad(probes, ((0, pad), (0, 0)))
        cp = jnp.pad(coarse_vals, ((0, pad), (0, 0)))
        qm = jnp.pad(jnp.ones((q,), jnp.bool_), (0, pad))
        vals, ids, dropped = lax.map(
            scan_tile,
            (
                lp.reshape(n_tiles, q_tile, luts.shape[1]),
                pp.reshape(n_tiles, q_tile, n_probes),
                cp.reshape(n_tiles, q_tile, n_probes),
                qm.reshape(n_tiles, q_tile),
            ),
        )
        vals = vals.reshape(-1, k)[:q]
        ids = ids.reshape(-1, k)[:q]
        dropped = jnp.sum(dropped)
    if not l2:
        vals = -vals
    return vals, ids, dropped


@traced("ivf_pq::search")
def search(
    index: IvfPqIndex,
    queries,
    k: int,
    n_probes: int = 20,
    filter: Optional[Bitset] = None,
    select_algo: str = "exact",
    backend: str = "auto",
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Approximate k-NN over the PQ-compressed lists
    (detail/ivf_pq_search.cuh:731). Returns (distances, indices); distances
    are PQ approximations — pipe through :mod:`raft_tpu.neighbors.refine`
    for exact re-ranking (the reference does the same, refine-inl.cuh:70).
    """
    res = res or current_resources()
    queries = jnp.asarray(queries).astype(jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries must be (q, {index.dim}), got {queries.shape}")
    n_probes = int(min(n_probes, index.n_lists))
    filter_attrs = None
    if filter is not None:
        from raft_tpu.resilience import faultpoint

        faultpoint("ivf_pq.search.filter")
        n_probes, _, f_rate, f_widen = _filtering.widen_plan(
            filter, n_probes, index.n_lists)
        filter_attrs = {"filter_pass_rate": round(f_rate, 6),
                        "filter_widen_x": round(f_widen, 4),
                        "filter_n_probes": n_probes}
    if not 0 < k <= n_probes * index.max_list_size:
        raise ValueError(f"k={k} out of range")
    if index.metric == "cosine":
        queries = queries / jnp.maximum(jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-30)

    from raft_tpu.ops.strip_scan import strip_eligible

    aligned = strip_eligible(index.max_list_size) and k <= 512
    pallas_ok = index.max_list_size % 128 == 0
    if index.list_codes.shape[-1] == 0:
        # cache-only streamed index: the int8 strip cache IS the payload —
        # no codes for the LUT/gather backends to read
        if not aligned:
            raise ValueError(
                "cache-only streamed index needs a strip-eligible "
                f"max_list_size (power-of-two multiple of 512 and k <= "
                f"512), got {index.max_list_size} / k={k}")
        backend = "ragged"
    if backend == "auto":
        # ragged decoded scan on TPU (the fast path); jnp gather elsewhere
        # (the exact-fp32 oracle; its take_along_axis crashes the TPU
        # runtime at large shapes, so it is never auto-picked there);
        # misaligned (old / small-group) indexes fall back to the LUT
        # kernel on TPU, and — if even 128-alignment is missing (legacy
        # 64-granule index, ADVICE.md round-2 high finding) — to the gather
        # path, which such small-list indexes can afford
        if jax.default_backend() == "tpu":
            backend = "ragged" if aligned else ("pallas" if pallas_ok else "gather")
        else:
            backend = "gather"
    if backend not in ("ragged", "pallas", "gather"):
        raise ValueError(f"unknown backend {backend!r}")
    if backend == "pallas" and index.codebook_kind == "cluster":
        # the LUT kernel's table is per query; PER_CLUSTER tables are per
        # list — served by the strip cache / gather paths instead
        backend = "ragged" if aligned and jax.default_backend() == "tpu" else "gather"
    scan_attrs = None
    if obs.enabled():
        q_obs = int(queries.shape[0])
        obs.add("ivf_pq.search.queries", q_obs)
        obs.add("ivf_pq.search.probes", q_obs * n_probes)
        # padded upper bound on candidate rows visited (the ragged backend's
        # actual work is ∝ real list fills; this is telemetry, not billing)
        obs.add("ivf_pq.search.rows_scanned",
                q_obs * n_probes * index.max_list_size)
        obs.add(f"ivf_pq.search.backend.{backend}", 1)
        scan_attrs = {"backend": backend, "queries": q_obs,
                      "probes": int(n_probes), "k": int(k)}
        if filter_attrs:
            scan_attrs.update(filter_attrs)
        # roofline note (round 15): static FLOP/byte model + strip
        # occupancy when the host already caches per-list lengths (the
        # ragged path; telemetry must never force a device sync)
        rot_dim_obs = int(index.rotation.shape[0])
        occ = None
        lens_cached = getattr(index, "_lens_np_cache", None)
        if backend == "ragged" and lens_cached is not None \
                and lens_cached.shape[0] == index.n_lists:
            from raft_tpu.ops.strip_scan import occupancy_stats
            kf_occ = min(int(k), 512)
            occ = obs_roofline.memo_occupancy(
                index,
                (id(lens_cached), q_obs, int(n_probes), kf_occ,
                 res.workspace_bytes),
                lambda: occupancy_stats(
                    lens_cached, index.max_list_size, q_obs, n_probes,
                    dim=rot_dim_obs, workspace_bytes=res.workspace_bytes,
                    kf=kf_occ))
        obs_roofline.note_dispatch(
            "ivf_pq.search",
            {"q": q_obs, "dim": index.dim, "n_lists": index.n_lists,
             "max_list_size": index.max_list_size,
             "pq_dim": index.pq_dim, "pq_bits": index.pq_bits,
             "n_probes": int(n_probes), "k": int(k),
             "rot_dim": rot_dim_obs},
            occupancy=occ)
    from raft_tpu.resilience import faultpoint

    faultpoint("ivf_pq.search.scan")
    # one scan-phase span regardless of backend (entered exactly once);
    # attrs are built inside the enabled gate above so the off path stays
    # one branch
    scan_span = obs.record_span("ivf_pq::scan", attrs=scan_attrs)
    if backend == "ragged":
        if not aligned:
            raise ValueError(
                f"ragged backend needs max_list_size = a power-of-two "
                f"multiple of 512, got {index.max_list_size}; rebuild with "
                "group_size=512 (or use backend='pallas'/'gather')"
            )
        # cosine included in _finalize_pq's fused dispatch
        with scan_span:
            return _search_ragged_pq(
                index, queries, int(k), n_probes, filter, select_algo, res
            )
    if backend == "pallas":
        if not pallas_ok:
            raise ValueError(
                f"pallas backend needs max_list_size % 128 == 0, got "
                f"{index.max_list_size}; rebuild with group_size=128 "
                "(or use backend='gather')"
            )
        p = n_probes
        n_codes = index.codebooks.shape[1]
        # per (list, slot): fp32 scores row + the bf16 gathered LUT row
        # (ADVICE.md: the luts_g block dominates at pq_bits=8 and must be
        # part of the budget)
        per_slot = index.max_list_size * 4 + index.pq_dim * n_codes * 2

        def _align16(v):
            return -(-max(16, int(v)) // 16) * 16

        # initial sizing: cap = 2x the mean per-list load; the workspace
        # constraint is on cap (the (n_lists, cap, ·) scores/LUT blocks),
        # shrinking the query tile shrinks the cap a tile needs
        q_tile = queries.shape[0]
        qpl_cap = _align16(2 * q_tile * p // index.n_lists)
        while index.n_lists * qpl_cap * per_slot > res.workspace_bytes and q_tile > 64:
            q_tile //= 2
            qpl_cap = _align16(2 * q_tile * p // index.n_lists)
        qpl_cap = min(qpl_cap, _align16(q_tile))

        # drop-detect + escalate (ADVICE.md medium finding — silent drops
        # degraded recall). A query probes each list at most once, so
        # cap >= q_tile provably cannot drop: the loop terminates with zero
        # drops. The gather backend is NOT a fallback here — large-shape
        # take_along_axis crashes the TPU runtime.
        with scan_span:
            while True:
                vals, ids, dropped = _search_impl_pallas(
                    queries, index.centers, index.rotation, index.codebooks,
                    index.list_codes, index.list_ids, index.b_sum, filter,
                    int(k), n_probes, index.metric, int(q_tile), int(qpl_cap),
                    select_algo, res.compute_dtype, jax.default_backend() != "tpu",
                    index.pq_dim, index.pq_bits,
                )
                dropped = int(dropped)
                if dropped == 0:
                    break
                if qpl_cap >= q_tile:
                    raise RuntimeError(
                        f"ivf_pq pallas scan dropped {dropped} pairs at "
                        f"qpl_cap={qpl_cap} >= q_tile={q_tile}; this cannot "
                        "happen — please report"
                    )
                qpl_cap = min(_align16(2 * qpl_cap), _align16(q_tile))
                if index.n_lists * qpl_cap * per_slot > res.workspace_bytes:
                    _log.warning(
                        "ivf_pq pallas scan exceeding workspace budget to avoid "
                        "dropping pairs (qpl_cap=%d); consider a larger "
                        "Resources.workspace_bytes", qpl_cap,
                    )
                _log.warning(
                    "ivf_pq pallas scan dropped %d probed pairs (skewed probes); "
                    "retrying with qpl_cap=%d (one retrace)", dropped, qpl_cap,
                )
    if backend == "gather":
        # tile budget: the (qt, p, m, s) code gather dominates
        per_query = max(1, n_probes * index.max_list_size * (index.pq_dim * 5 + 8))
        q_tile = int(max(1, min(queries.shape[0], res.workspace_bytes // per_query)))
        with scan_span:
            vals, ids = _search_impl_jnp(
                queries, index.centers, index.rotation, index.codebooks,
                index.list_codes, index.list_ids, index.b_sum, filter,
                int(k), n_probes, index.metric, q_tile, select_algo,
                res.compute_dtype, index.pq_dim, index.pq_bits,
                index.codebook_kind == "cluster",
            )
    if index.metric == "cosine":
        vals = jnp.where(ids >= 0, 1.0 - vals, jnp.inf)
    return vals, ids


# ---------------------------------------------------------------------------
# Paged search (serving layer): scan a PagedListStore's encoded pages
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("pq_dim", "pq_bits"))
def _row_b_sum(centers, rotation, codebooks, codes, labels, pq_dim, pq_bits):
    """Per-row list-side LUT half for freshly encoded rows: the SAME
    B[l, s, c] table and Σ_s reduction as :func:`_compute_b_sum`, gathered
    by each row's label — paged↔packed parity needs the aux bitwise
    equal, not merely close. Subspace codebooks only (the serving store's
    constraint)."""
    n_lists = centers.shape[0]
    n_codes = codebooks.shape[1]
    dsub = codebooks.shape[2]
    rot_dim = pq_dim * dsub
    rc = (_pad_rot(centers, rot_dim) @ rotation.T).reshape(n_lists, pq_dim, dsub)
    B = 2.0 * jnp.einsum("lsd,scd->lsc", rc, codebooks,
                         preferred_element_type=jnp.float32)
    B = B + jnp.sum(codebooks * codebooks, axis=2)[None]
    s_off = (jnp.arange(pq_dim, dtype=jnp.int32) * n_codes)[None, :]
    flat_idx = _codes_view(codes, pq_dim, pq_bits).astype(jnp.int32) + s_off
    picked = jnp.take_along_axis(
        B.reshape(n_lists, -1)[labels], flat_idx, axis=1)
    return jnp.sum(picked, axis=1)


@jax.jit
def _center_rot_sqnorm(centers, rotation):
    """‖R·c̃_l‖² per list — the per-list constant of the decoded-cache
    scan bias (:func:`_ragged_bias_pq`'s ``rc2``), shared with the paged
    store so its per-row bias pool stays bitwise-parity with the packed
    formula."""
    return dist_mod.sqnorm(_pad_rot(centers, rotation.shape[0]) @ rotation.T)


@functools.partial(jax.jit, static_argnames=("pq_dim", "pq_bits"))
def _decode_code_rows(codebooks, codes, scale, pq_dim, pq_bits):
    """int8 decoded-residual rows for freshly encoded codes — the per-row
    twin of :func:`_decode_lists_scaled` (same quantized codebook, same
    flat gather), so a paged store's incremental cache rows are bitwise
    identical to the packed decode of the same codes. Subspace codebooks
    only (the serving store's constraint)."""
    n_codes, dsub = codebooks.shape[1], codebooks.shape[2]
    rot_dim = pq_dim * dsub
    cb_q = jnp.clip(jnp.round(codebooks / scale), -127, 127).astype(jnp.int8)
    cb_flat = cb_q.reshape(pq_dim * n_codes, dsub)
    s_off = (jnp.arange(pq_dim, dtype=jnp.int32) * n_codes)[None, :]
    cv = _codes_view(codes, pq_dim, pq_bits)
    resid = jnp.take(cb_flat, cv.astype(jnp.int32) + s_off, axis=0)
    return resid.reshape(codes.shape[0], rot_dim)


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "q_tile", "select_algo",
                     "compute_dtype", "pq_dim", "pq_bits"),
)
def _paged_impl(
    queries, centers, rotation, codebooks, pages, page_ids, page_aux, table,
    filter, k, n_probes, metric, q_tile, select_algo, compute_dtype,
    pq_dim, pq_bits,
):
    """Paged-store scan: the gather-backend LUT search
    (:func:`_search_impl_jnp`) re-shaped over (page-table, page) instead of
    a padded list axis. Every per-candidate op is kept identical so a
    fully-compacted store is bit-parity with the packed scan; empty page
    slots self-mask through the +inf aux (the packed padding convention)
    and the ``ids >= 0`` validity mask covers tombstones. All operand
    shapes derive from CAPACITY (page pool, table width) — appends and
    tombstones re-dispatch this same program."""
    # ledger registration (runs at trace time only): a growth retrace
    # lands attributed to the operand that grew (pages / table)
    obs_compile.trace_event(
        "ivf_pq.paged_scan", queries=queries, centers=centers,
        rotation=rotation, codebooks=codebooks, pages=pages,
        page_ids=page_ids, page_aux=page_aux, table=table, filter=filter,
        static={"k": k, "n_probes": n_probes, "metric": metric,
                "q_tile": q_tile, "select_algo": select_algo,
                "compute_dtype": compute_dtype, "pq_dim": pq_dim,
                "pq_bits": pq_bits})
    q, dim = queries.shape
    l2 = metric in ("sqeuclidean", "euclidean")
    if l2:
        coarse = dist_mod._expanded_distance(
            queries, centers, "sqeuclidean", compute_dtype, "highest"
        )
    else:
        coarse = -dist_mod.matmul_t(queries, centers, compute_dtype, "highest")
    coarse_vals, probes = select_k(coarse, n_probes, select_min=True,
                                   algo=select_algo)
    n_codes = codebooks.shape[1]
    luts = _query_luts(queries, rotation, codebooks, metric, jnp.float32)
    luts = luts.reshape(q, -1)
    s_off = (jnp.arange(pq_dim, dtype=jnp.int32) * n_codes)

    def scan_tile(args):
        q_lut, probe_blk, cvals_blk = args  # (qt, ·), (qt, p), (qt, p)
        tbl = table[probe_blk]                        # (qt, p, W)
        safe = jnp.maximum(tbl, 0)
        codes = _codes_view(pages[safe], pq_dim, pq_bits) \
            .astype(jnp.int32)                        # (qt, p, W, R, s)
        ids = jnp.where(tbl[..., None] >= 0, page_ids[safe], -1)
        flat_idx = codes + s_off[None, None, None, None, :]
        picked = jax.vmap(lambda lut, idx: jnp.take(lut, idx, axis=0))(
            q_lut, flat_idx)
        d = jnp.sum(picked, axis=4) + page_aux[safe] \
            + cvals_blk[:, :, None, None]
        if l2:
            d = jnp.maximum(d, 0.0)
            if metric == "euclidean":
                d = jnp.sqrt(d)
        flat_ids = ids.reshape(ids.shape[0], -1)
        d = d.reshape(flat_ids.shape)
        valid = flat_ids >= 0
        if filter is not None:
            valid = valid & filter.test(flat_ids)
        d = jnp.where(valid, d, jnp.inf)
        vals, sel = select_k(d, k, select_min=True, algo=select_algo)
        out_ids = jnp.where(jnp.isinf(vals), -1,
                            jnp.take_along_axis(flat_ids, sel, axis=1))
        return vals, out_ids

    vals, ids = map_row_tiles(scan_tile, (luts, probes, coarse_vals), q_tile)
    if not l2:
        vals = -vals  # back to raw inner product (bigger = closer)
    return vals, ids


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "select_algo",
                     "compute_dtype", "q_tile", "interpret", "impl"),
)
def _paged_fused_pq(queries, centers, rotation, cache_pool, bias_pool,
                    page_ids, table, chain_pages, decoded_scale, filter,
                    k, n_probes, metric, select_algo, compute_dtype,
                    q_tile, interpret, impl):
    """The ENTIRE paged PQ Pallas search as one jit: coarse gemm + query
    rotation, device strip planning, the page-table DMA kernel over the
    int8 decoded-residual cache pool, merge, finalize — the
    ``_ragged_fused_pq`` shape over page chains. All operands are
    capacity-shaped (zero-recompile serving contract); the exact
    −2⟨q, R·c_l⟩ term rides the merge's pair_const exactly like the
    packed path."""
    from raft_tpu.ops.strip_scan import paged_strip_search_traced

    obs_compile.trace_event(
        "ivf_pq.paged_pallas", queries=queries, centers=centers,
        rotation=rotation, cache_pool=cache_pool, bias_pool=bias_pool,
        page_ids=page_ids, table=table, chain_pages=chain_pages,
        decoded_scale=decoded_scale, filter=filter,
        static={"k": k, "n_probes": n_probes, "metric": metric,
                "select_algo": select_algo, "compute_dtype": compute_dtype,
                "q_tile": q_tile, "interpret": interpret, "impl": impl})
    l2 = metric in ("sqeuclidean", "euclidean")
    sa = ("packed" if select_algo == "exact" and not interpret
          and centers.shape[0] <= 4096 else select_algo)
    # the packed path's shared probe prep (bitwise parity by
    # construction); the bias comes from the store-maintained pool —
    # already rc2 + b_sum per row — instead of _ragged_bias_pq
    probes, qr, pair_const = _pq_probe_prep(
        queries, centers, rotation, n_probes, sa, l2)
    alpha = -2.0 if l2 else -1.0
    bias = _filtering.apply_filter_bias(bias_pool, page_ids, filter)
    vals, ids = paged_strip_search_traced(
        qr * decoded_scale, probes, cache_pool, bias, page_ids, table,
        chain_pages, int(k), int(k), alpha, q_tile, interpret,
        pair_const=pair_const, impl=impl)
    from raft_tpu.neighbors.ivf_flat import _finalize_ragged

    return _finalize_ragged(vals, ids, queries, metric)


@traced("ivf_pq::search_paged")
def search_paged(
    store,
    queries,
    k: int,
    n_probes: int = 20,
    filter: Optional[Bitset] = None,
    select_algo: str = "exact",
    backend: str = "auto",
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Approximate k-NN over a mutable paged code store
    (:class:`raft_tpu.serving.PagedListStore`, kind ``"ivf_pq"``): same
    contract as :func:`search`, but the store keeps serving while rows
    stream in/out — no repack, and steady-state mutations never recompile
    this scan (its shapes depend only on store capacity).

    ``backend``: "paged_pallas" (page-table DMA strip kernel over the
    int8 decoded cache pool — the TPU engine, interpret-mode elsewhere),
    "paged_jnp" (its bit-parity jnp reference), "gather" (LUT gather scan
    — CPU default), or "auto"."""
    if store.kind != "ivf_pq":
        raise ValueError(f"expected an ivf_pq store, got {store.kind!r}")
    res = res or current_resources()
    queries = jnp.asarray(queries).astype(jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != store.dim:
        raise ValueError(f"queries must be (q, {store.dim}), got {queries.shape}")
    n_probes = int(min(n_probes, store.n_lists))
    if filter is None:
        filter = getattr(store, "filter", None)
    filter_attrs = None
    if filter is not None:
        from raft_tpu.resilience import faultpoint

        faultpoint("ivf_pq.search.filter")
        n_probes, _, f_rate, f_widen = _filtering.widen_plan(
            filter, n_probes, store.n_lists)
        filter_attrs = {"filter_pass_rate": round(f_rate, 6),
                        "filter_widen_x": round(f_widen, 4),
                        "filter_n_probes": n_probes}
    from raft_tpu.neighbors.ivf_flat import (_paged_plan_static,
                                             paged_backend_auto)

    if backend == "auto":
        backend = paged_backend_auto(store, k)
    if backend not in ("gather", "paged_pallas", "paged_jnp"):
        raise ValueError(f"unknown backend {backend!r}")
    # one ATOMIC store snapshot: pool/table read separately could tear
    # against a concurrent upsert's capacity growth
    if backend == "gather":
        pages, page_ids, page_aux, table = store.scan_state()
    else:
        cache_pool, bias_pool, _, page_ids, table, chain_pages = \
            store.paged_scan_state()
    width = int(table.shape[1])
    if not 0 < k <= n_probes * width * store.page_rows:
        raise ValueError(f"k={k} out of range")
    if store.metric == "cosine":
        queries = queries / jnp.maximum(
            jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-30)
    scan_attrs = None
    if obs.enabled():
        q_obs = int(queries.shape[0])
        obs.add("ivf_pq.search_paged.queries", q_obs)
        obs.add("ivf_pq.search_paged.probes", q_obs * n_probes)
        obs.add(f"ivf_pq.search_paged.backend.{backend}", 1)
        scan_attrs = {"backend": backend, "queries": q_obs,
                      "probes": int(n_probes), "k": int(k),
                      "table_width": width}
        if filter_attrs:
            scan_attrs.update(filter_attrs)
        if backend == "gather":
            # roofline note (round 15): LUT-scan cost over the capacity-
            # padded page chains (no cross-query sharing on this path)
            obs_roofline.note_dispatch(
                "ivf_pq.paged_scan",
                {"q": q_obs, "dim": store.dim, "n_lists": store.n_lists,
                 "page_rows": store.page_rows, "table_width": width,
                 "pq_dim": store.pq_dim, "pq_bits": store.pq_bits,
                 "n_probes": int(n_probes), "k": int(k),
                 "rot_dim": int(store.rotation.shape[0])})
        else:
            from raft_tpu.ops.strip_scan import paged_occupancy_stats
            occ = obs_roofline.memo_occupancy(
                store,
                (store.pages_used, store.size, store.tombstones, width,
                 q_obs, int(n_probes), int(k), res.workspace_bytes),
                lambda: paged_occupancy_stats(
                    width, store.page_rows, store._list_pages, store.size,
                    store.tombstones, q_obs, int(n_probes), int(k),
                    store._cache_dim, workspace_bytes=res.workspace_bytes,
                    dim=store._cache_dim))
            obs_roofline.note_dispatch(
                "ivf_pq.paged_pallas",
                {"q": q_obs, "dim": store.dim, "n_lists": store.n_lists,
                 "page_rows": store.page_rows, "table_width": width,
                 "pq_dim": store.pq_dim, "pq_bits": store.pq_bits,
                 "n_probes": int(n_probes), "k": int(k),
                 "rot_dim": int(store.rotation.shape[0])},
                occupancy=occ)
    from raft_tpu.resilience import faultpoint

    if backend != "gather":
        interpret = jax.default_backend() != "tpu"
        q_tile = min(_paged_plan_static(store, n_probes, k, res,
                                        store._cache_dim),
                     queries.shape[0])
        impl = "pallas" if backend == "paged_pallas" else "jnp"
        faultpoint("ivf_pq.search_paged.scan")
        with obs.record_span("ivf_pq::paged_pallas", attrs=scan_attrs):
            with obs_compile.watch():
                # cosine is already folded by _finalize_ragged inside the
                # fused dispatch (the packed ragged path's convention)
                return _paged_fused_pq(
                    queries, store.centers, store.rotation, cache_pool,
                    bias_pool, page_ids, table, chain_pages,
                    store.decoded_scale, filter, int(k), n_probes,
                    store.metric, select_algo, res.compute_dtype,
                    int(q_tile), interpret, impl)
    # the (qt, p, W, R, s) unpacked-code gather dominates the working set
    per_query = max(1, n_probes * width * store.page_rows
                    * (store.pq_dim * 5 + 8))
    q_tile = int(max(1, min(queries.shape[0],
                            res.workspace_bytes // per_query)))
    faultpoint("ivf_pq.search_paged.scan")
    with obs.record_span("ivf_pq::paged_scan", attrs=scan_attrs):
        # ledger watch: a dispatch that (re)traces gets its wall-clock
        # stamped onto the ledger record (steady state stamps nothing)
        with obs_compile.watch():
            vals, ids = _paged_impl(
                queries, store.centers, store.rotation, store.codebooks,
                pages, page_ids, page_aux, table, filter,
                int(k), n_probes, store.metric, q_tile, select_algo,
                res.compute_dtype, store.pq_dim, store.pq_bits,
            )
    if store.metric == "cosine":
        vals = jnp.where(ids >= 0, 1.0 - vals, jnp.inf)
    return vals, ids


def reconstruct_rows(centers, rotation, codebooks, codes, labels,
                     pq_dim: int, pq_bits: int, dim: Optional[int] = None):
    """Approximate original vectors from packed PQ codes: the exact float
    codeword per subspace (NOT the int8 scan cache), un-rotated back to
    the input space and re-centered by each row's list centroid.
    Assignment-grade — the maintenance re-cluster's row source when the
    raw vectors are gone. Re-encoding a reconstruction against the SAME
    centers reproduces the codes exactly (the codeword is each subspace's
    nearest codeword to itself); against moved centers it is the
    principled nearest re-quantization."""
    codes = jnp.asarray(codes)
    labels = jnp.asarray(labels, jnp.int32)
    n_codes, dsub = int(codebooks.shape[1]), int(codebooks.shape[2])
    cb_flat = jnp.asarray(codebooks).reshape(pq_dim * n_codes, dsub)
    s_off = (jnp.arange(pq_dim, dtype=jnp.int32) * n_codes)[None, :]
    cv = _codes_view(codes, pq_dim, pq_bits).astype(jnp.int32)
    resid_rot = jnp.take(cb_flat, cv + s_off, axis=0).reshape(
        codes.shape[0], pq_dim * dsub)
    resid = linalg.unrotate_rows(resid_rot, rotation, "dense")
    d = int(centers.shape[1]) if dim is None else int(dim)
    return centers[labels] + resid[:, :d]
