"""ANN indexes — the crown jewels (reference cpp/include/raft/neighbors/).

Re-designed TPU-first:
  * `brute_force` — tiled exact kNN (reference brute_force-inl.cuh:157,
    detail/knn_brute_force.cuh:61): gemm distances + streaming top-k merge
    under `lax.scan`, out-of-core over dataset tiles.
  * `ivf_flat` — padded/bucketed dense cluster lists + validity masks in place
    of the CUDA interleaved-group layout (ivf_flat_types.hpp:47).
  * `ivf_pq` — PQ codebooks + LUT scan (the flagship kernel), bf16/int8 LUT
    compression as the fp8 analog (detail/ivf_pq_fp_8bit.cuh).
  * `ivf_bq` — RaBitQ-style 1-bit sign codes + unbiased correction scalars,
    scanned as ±1 MXU contractions (ops/bq_scan.py), exact refine on top.
  * `cagra` — fixed-degree graph + fixed-iteration best-first search with
    sort-based dedup instead of device hashmaps (detail/cagra/hashmap.hpp).
  * `refine` — exact re-ranking of candidate lists (refine-inl.cuh:70).
All share the filter protocol (`Bitset` prefilter, sample_filter.cuh:31) and
container serialization (core/serialize.py).
"""

from raft_tpu.neighbors import (
    ball_cover,
    brute_force,
    cagra,
    epsilon_neighborhood,
    hybrid,
    ivf_bq,
    ivf_flat,
    ivf_pq,
    nn_descent,
    refine,
)
from raft_tpu.neighbors.epsilon_neighborhood import eps_neighbors

__all__ = [
    "ball_cover", "brute_force", "cagra", "epsilon_neighborhood",
    "eps_neighbors", "hybrid", "ivf_bq", "ivf_flat", "ivf_pq", "nn_descent",
    "refine",
]
