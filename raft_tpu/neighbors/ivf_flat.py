"""IVF-Flat: inverted-file index with uncompressed (flat) vectors.

Reference surface: raft::neighbors::ivf_flat — build (ivf_flat-inl.cuh:65 →
detail/ivf_flat_build.cuh, kmeans_balanced trainer at :384), search
(ivf_flat-inl.cuh:516 → detail/ivf_flat_search-inl.cuh:38: coarse distance +
select_k of n_probes lists :130 → interleaved list scan :149 → final select_k
:194), extend, serialize (ivf_flat_serialize.cuh); params ivf_flat_types.hpp
(n_lists, kmeans_n_iters, kmeans_trainset_fraction, adaptive_centers).

TPU design. The reference stores each list as variable-length interleaved
groups of 32 vectors (kIndexGroupSize, ivf_flat_types.hpp:47) and launches one
CTA per (query, probe). Variable-length anything is hostile to XLA's static
shapes, so lists here are **padded dense blocks**: one (n_lists, max_list_size,
dim) array with per-entry validity given by ``list_ids >= 0``. Balanced
k-means (cluster/kmeans_balanced.py) bounds the skew, so the padding overhead
is a small constant factor; max_list_size is rounded up to a multiple of 32
(the kIndexGroupSize analog — keeps the scan dimension MXU/VPU aligned).

Search is two select_k stages around one gather+batched-matmul scan:
coarse distances ride the MXU as a single (q, n_lists) gemm; the list scan
gathers (q_tile, n_probes, max_list_size, dim) candidate blocks from HBM and
reduces them with an einsum — HBM-bandwidth-bound, tiled over queries by the
Resources workspace budget so the gather never blows past the budget.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs
from raft_tpu.obs import compile as obs_compile
from raft_tpu.obs import roofline as obs_roofline
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import _filtering
from raft_tpu.neighbors import _packing
from raft_tpu.neighbors._packing import pack_lists, unpack_lists
from raft_tpu.core.trace import traced
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.serialize import load_arrays, save_arrays
from raft_tpu.ops import distance as dist_mod
from raft_tpu.ops.select_k import select_k
from raft_tpu.utils.tiling import map_row_tiles

SUPPORTED_METRICS = ("sqeuclidean", "euclidean", "inner_product", "cosine")


@dataclass(frozen=True)
class IvfFlatParams:
    """Build params (ivf_flat_types.hpp index_params analog)."""

    n_lists: int = 1024
    metric: str = "sqeuclidean"
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    # per-list occupancy cap: -1 = auto (4× mean, group-aligned), 0 = off.
    # Overflow rows spill to their second-nearest list (_packing.spill_to_cap)
    list_size_cap: int = -1
    # list padding granule: 0 = auto (512 == strip_scan.MC when the mean
    # list is large enough to amortize it — required for the ragged TPU
    # backend — else 64, kIndexGroupSize-style, to keep small indexes small)
    group_size: int = 0
    seed: int = 0

    def __post_init__(self):
        m = dist_mod.canonical_metric(self.metric)
        if m not in SUPPORTED_METRICS:
            raise ValueError(f"ivf_flat supports {SUPPORTED_METRICS}, got {self.metric!r}")
        object.__setattr__(self, "metric", m)


@jax.tree_util.register_pytree_node_class
@dataclass
class IvfFlatIndex:
    """Cluster centers + padded per-list vector blocks.

    ``list_ids[l, j] == -1`` marks padding; valid entries hold the source row
    id. ``list_norms`` caches per-entry squared L2 norms for the L2 scan.
    For cosine, vectors and centers are stored L2-normalized and the scan runs
    as inner product (the reference normalizes the same way for
    CosineExpanded).
    """

    centers: jax.Array  # (n_lists, dim) fp32
    list_data: jax.Array  # (n_lists, max_list_size, dim)
    list_ids: jax.Array  # (n_lists, max_list_size) int32, -1 = padding
    list_norms: Optional[jax.Array]  # (n_lists, max_list_size) fp32, L2 only
    metric: str
    # list padding granule used at build; extend() reuses it instead of
    # inferring from max_list_size (ADVICE.md round-2). 0 = unknown (legacy).
    group_size: int = 0

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def max_list_size(self) -> int:
        return self.list_data.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_ids >= 0))

    def list_sizes(self) -> jax.Array:
        return jnp.sum(self.list_ids >= 0, axis=1).astype(jnp.int32)

    def tree_flatten(self):
        return (self.centers, self.list_data, self.list_ids, self.list_norms), (self.metric, self.group_size)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    # -- persistence (ivf_flat_serialize.cuh analog) -----------------------
    def save(self, path) -> None:
        arrays = {
            "centers": self.centers,
            "list_data": self.list_data,
            "list_ids": self.list_ids,
        }
        if self.list_norms is not None:
            arrays["list_norms"] = self.list_norms
        save_arrays(path, {"kind": "ivf_flat", "metric": self.metric,
                           "group_size": self.group_size}, arrays)

    @classmethod
    def load(cls, path) -> "IvfFlatIndex":
        meta, arrays = load_arrays(path)
        if meta.get("kind") != "ivf_flat":
            raise ValueError(f"not an ivf_flat index: {meta.get('kind')}")
        return cls(
            jnp.asarray(arrays["centers"]),
            jnp.asarray(arrays["list_data"]),
            jnp.asarray(arrays["list_ids"]),
            jnp.asarray(arrays["list_norms"]) if "list_norms" in arrays else None,
            meta["metric"],
            int(meta.get("group_size", 0)),
        )


# ---------------------------------------------------------------------------
# Build
# ---------------------------------------------------------------------------


def _pack_lists(dataset, row_ids, labels, n_lists: int, group: int = 0):
    """Padded per-list blocks (the ivf_list fill, detail/ivf_flat_build.cuh
    build_index; group rounding per kIndexGroupSize / strip_scan.MC)."""
    if group <= 0:
        group = _packing.auto_group_size(dataset.shape[0], n_lists)
    return pack_lists(dataset, row_ids, labels, n_lists, group,
                      pow2_chunks=group == 512)


@traced("ivf_flat::build")
def build(
    dataset,
    params: IvfFlatParams = IvfFlatParams(),
    res: Optional[Resources] = None,
) -> IvfFlatIndex:
    """Train the coarse quantizer and fill the lists (ivf_flat-inl.cuh:65).

    Trains balanced k-means on a ``kmeans_trainset_fraction`` subsample
    (ivf_flat_types.hpp:55), then assigns every row to its nearest center.
    """
    res = res or current_resources()
    dataset = jnp.asarray(dataset)
    n, dim = dataset.shape
    if params.n_lists > n:
        raise ValueError(f"n_lists={params.n_lists} > n_rows={n}")

    work = dataset.astype(jnp.float32)
    if params.metric == "cosine":
        work = work / jnp.maximum(jnp.linalg.norm(work, axis=1, keepdims=True), 1e-30)

    km_metric = "inner_product" if params.metric in ("cosine", "inner_product") else "sqeuclidean"
    km = kmeans_balanced.KMeansBalancedParams(
        n_iters=params.kmeans_n_iters, metric=km_metric, seed=params.seed
    )

    n_train = max(params.n_lists, int(n * params.kmeans_trainset_fraction))
    # phase span (round-8): parented under the @traced entry span, so trace
    # exports break a build into train vs pack time
    with obs.record_span("ivf_flat::coarse_train"):
        if n_train < n:
            key = jax.random.key(params.seed)
            # with-replacement sampling: the ~n_train²/2n duplicate rate is
            # noise for k-means, and it avoids the O(n log n) permutation
            # program that choice(replace=False) compiles (round-3: ~25 s of
            # XLA compile)
            train_rows = jax.random.randint(key, (n_train,), 0, n)
            centers = kmeans_balanced.fit(work[train_rows], params.n_lists, km, res=res)
            labels = kmeans_balanced.predict(work, centers, km, res=res)
        else:
            centers, labels = kmeans_balanced.fit_predict(work, params.n_lists, km, res=res)

    if obs.enabled():
        obs.add("ivf_flat.build.rows", n)
        obs.add("ivf_flat.build.lists", params.n_lists)

    group = params.group_size or _packing.auto_group_size(n, params.n_lists)
    cap = params.list_size_cap
    if cap < 0:
        cap = _packing.auto_list_cap(n, params.n_lists, group)
    if cap:
        labels = _packing.spill_to_cap(work, centers, labels, km_metric, cap)

    # integer datasets (uint8/int8, the big-ann on-disk formats) are stored
    # in their own dtype — 4× less HBM than fp32; every scan upcasts to the
    # bf16 compute type on the fly (exact for |v| <= 256)
    store = (dataset if (jnp.issubdtype(dataset.dtype, jnp.integer)
                         and params.metric != "cosine") else work)
    with obs.record_span("ivf_flat::pack"):
        row_ids = jnp.arange(n, dtype=jnp.int32)
        list_data, list_ids = _pack_lists(store, row_ids, labels, params.n_lists, group)
        list_norms = None
        if params.metric in ("sqeuclidean", "euclidean"):
            list_norms = dist_mod.sqnorm(list_data, axis=2)
    return IvfFlatIndex(centers, list_data, list_ids, list_norms, params.metric, group)


@traced("ivf_flat::extend")
def extend(index: IvfFlatIndex, new_vectors, new_ids=None, res: Optional[Resources] = None) -> IvfFlatIndex:
    """Add vectors to an existing index (ivf_flat extend,
    detail/ivf_flat_build.cuh extend). Assigns to the fixed centers and
    repacks the lists (padded blocks are immutable, so extension is a repack
    rather than the reference's in-place list append)."""
    res = res or current_resources()
    new_vectors = jnp.asarray(new_vectors).astype(jnp.float32)
    if new_vectors.shape[1] != index.dim:
        raise ValueError(f"dim mismatch: {new_vectors.shape[1]} != {index.dim}")
    if index.metric == "cosine":
        new_vectors = new_vectors / jnp.maximum(
            jnp.linalg.norm(new_vectors, axis=1, keepdims=True), 1e-30
        )

    old_vecs, old_ids, old_labels = unpack_lists(index.list_data, index.list_ids)

    if new_ids is None:
        start = int(jnp.max(old_ids) + 1) if old_ids.size else 0
        new_ids = jnp.arange(start, start + new_vectors.shape[0], dtype=jnp.int32)
    else:
        new_ids = jnp.asarray(new_ids, jnp.int32)

    km_metric = (
        "inner_product" if index.metric in ("cosine", "inner_product") else "sqeuclidean"
    )
    new_labels = kmeans_balanced.predict(
        new_vectors, index.centers, kmeans_balanced.KMeansBalancedParams(metric=km_metric), res=res
    )
    # persisted granule; legacy indexes (group_size 0) fall back to inference
    group = index.group_size or (512 if index.max_list_size % 512 == 0 else 64)
    total = int(old_ids.shape[0]) + int(new_vectors.shape[0])
    cap = _packing.auto_list_cap(total, index.n_lists, group)
    new_labels = _packing.spill_to_cap(
        new_vectors, index.centers, new_labels, km_metric, cap,
        base_counts=index.list_sizes(),
    )

    if (jnp.issubdtype(index.list_data.dtype, jnp.integer)
            and new_vectors.dtype != index.list_data.dtype):
        # keep the integer-storage invariant (4× HBM) instead of silently
        # promoting the whole index to fp32; integer datasets extend with
        # integer rows, so the round/clip is exact in the expected case —
        # and warn when it is NOT (ADVICE r3: fractional / out-of-range
        # vectors used to lose precision with no signal)
        info = jnp.iinfo(index.list_data.dtype)
        new_store = jnp.clip(jnp.round(new_vectors), info.min, info.max) \
            .astype(index.list_data.dtype)
        # one scalar fetch — extend() is a whole-index repack with host
        # syncs already, so the round-trip is noise here (review r4 noted)
        err = float(jnp.max(jnp.abs(new_store.astype(jnp.float32)
                                    - new_vectors)))
        if err > 0.5:
            from raft_tpu.core.logger import get_logger

            get_logger().warning(
                f"ivf_flat.extend: quantizing float vectors into "
                f"{index.list_data.dtype} storage loses up to {err:.3g} "
                "per component (out-of-range or fractional inputs); "
                "rebuild with fp32 storage if that matters")
    else:
        new_store = new_vectors.astype(index.list_data.dtype) \
            if new_vectors.dtype != index.list_data.dtype else new_vectors
    all_vecs = jnp.concatenate([old_vecs, new_store])
    all_ids = jnp.concatenate([old_ids, new_ids])
    all_labels = jnp.concatenate([old_labels, new_labels])
    list_data, list_ids = _pack_lists(all_vecs, all_ids, all_labels, index.n_lists, group)
    list_norms = None
    if index.metric in ("sqeuclidean", "euclidean"):
        list_norms = dist_mod.sqnorm(list_data, axis=2)
    return IvfFlatIndex(index.centers, list_data, list_ids, list_norms, index.metric, group)


# ---------------------------------------------------------------------------
# Search
# ---------------------------------------------------------------------------


def _lens_np(index):
    """Host-cached per-list entry counts: planning needs them every search
    call, and refetching would cost a device sync per call."""
    cached = getattr(index, "_lens_np_cache", None)
    if cached is None or cached.shape[0] != index.n_lists:
        import numpy as np

        cached = np.asarray(index.list_sizes())
        try:
            index._lens_np_cache = cached
        except AttributeError:  # frozen/immutable containers: just recompute
            pass
    return cached


@functools.partial(
    jax.jit,
    static_argnames=("n_probes", "metric", "select_algo", "compute_dtype"),
)
def _coarse_probes(queries, centers, n_probes, metric, select_algo, compute_dtype):
    """Stage 1 alone: each query's top-n_probes list ids (q, p) int32
    (detail/ivf_flat_search-inl.cuh:130)."""
    if metric in ("sqeuclidean", "euclidean"):
        coarse = dist_mod._expanded_distance(
            queries, centers, "sqeuclidean", compute_dtype, "highest"
        )
    else:
        coarse = -dist_mod.matmul_t(queries, centers, compute_dtype, "highest")
    _, probes = select_k(coarse, n_probes, select_min=True, algo=select_algo)
    return probes


@functools.partial(jax.jit, static_argnames=("mode",))
def _ragged_bias(list_ids, list_norms, filter, mode: str):
    """Per-entry additive bias for the ragged scan: ‖x‖² for L2, 0 for
    ip/cosine; +inf at padding and filtered-out entries (the shared
    :func:`_filtering.apply_filter_bias` rule — one copy across the
    families)."""
    base = list_norms if mode == "l2" else jnp.zeros_like(list_ids, jnp.float32)
    bias = jnp.where(list_ids >= 0, base, jnp.inf).astype(jnp.float32)
    return _filtering.apply_filter_bias(bias, list_ids, filter)


@functools.partial(jax.jit, static_argnames=("metric",))
def _finalize_ragged(vals, ids, queries, metric):
    """One fused dispatch for the score finalization (each eager op here
    used to cost a ~15-20 ms runtime dispatch on the tunneled TPU)."""
    if metric in ("sqeuclidean", "euclidean"):
        vals = jnp.maximum(vals + dist_mod.sqnorm(queries)[:, None], 0.0)
        if metric == "euclidean":
            vals = jnp.sqrt(vals)
        return jnp.where(ids >= 0, vals, jnp.inf), ids
    if metric == "cosine":
        return jnp.where(ids >= 0, 1.0 + vals, jnp.inf), ids
    # inner_product: flip back to "larger is better" values
    return jnp.where(ids >= 0, -vals, -jnp.inf), ids


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "select_algo",
                     "compute_dtype", "classes", "class_counts", "q_tile",
                     "interpret"),
)
def _ragged_fused(queries, centers, list_data, bias, list_ids, cls_ord,
                  k, n_probes, metric, select_algo, compute_dtype,
                  classes, class_counts, q_tile, interpret):
    """The ENTIRE ragged search — coarse gemm, device strip planning, strip
    kernel, merge, finalize — as one jit: one runtime dispatch, zero host
    syncs (round-4; the per-tile grid-count fetch used to serialize every
    call at ~15-20 ms dispatch + RTT on the tunneled runtime, which is why
    an index probing 3% of the data lost to brute force at 1M rows)."""
    from raft_tpu.ops.strip_scan import strip_search_traced

    # ledger registration for the TPU-default backend too (trace time
    # only): a retrace on the platform of record must not be invisible
    obs_compile.trace_event(
        "ivf_flat.search_ragged", queries=queries, centers=centers,
        list_data=list_data, bias=bias, list_ids=list_ids, cls_ord=cls_ord,
        static={"k": k, "n_probes": n_probes, "metric": metric,
                "select_algo": select_algo, "compute_dtype": compute_dtype,
                "classes": classes, "class_counts": class_counts,
                "q_tile": q_tile, "interpret": interpret})

    # "exact" probe selection rides the packed iter (half the VPU passes)
    # only while n_lists keeps the index bits cheap: the perturbation is
    # 2^-(23-ceil(log2 n_lists)) relative — ≤ 5e-4 at 4096 lists, where it
    # only reorders boundary-tie lists (recall-neutral, measured). Larger
    # n_lists would steal real mantissa (ADVICE r4 medium), so "exact" is
    # honored literally there.
    sa = ("packed" if select_algo == "exact" and not interpret
          and centers.shape[0] <= 4096 else select_algo)
    probes = _coarse_probes(queries, centers, n_probes, metric, sa,
                            compute_dtype)
    l2 = metric in ("sqeuclidean", "euclidean")
    vals, ids = strip_search_traced(
        queries, probes, list_data, bias, list_ids, cls_ord,
        classes, class_counts, int(k), int(k), -2.0 if l2 else -1.0,
        q_tile, interpret,
    )
    return _finalize_ragged(vals, ids, queries, metric)


def _ragged_plan_static(index, n_probes, k, res, dim):
    """Host-cached static planning facts for the fused path: length classes,
    per-class list counts, the device class-ordinal array, and the query
    tile size. All derive from build-time state (list lengths), so they are
    cached on the index instance."""
    import numpy as np

    from raft_tpu.ops import strip_scan as ss

    cached = getattr(index, "_ragged_static_cache", None)
    if cached is None:
        lens_np = _lens_np(index)
        classes, cls_ord_np = ss.class_info(lens_np, dim=dim)
        classes = tuple(classes)  # hashable: jit static arg
        cached = (classes, ss.class_counts_of(cls_ord_np, len(classes)),
                  jnp.asarray(cls_ord_np))
        try:
            index._ragged_static_cache = cached
        except AttributeError:
            pass
    classes, class_counts, cls_ord = cached
    q_tile = ss.fit_q_tile(1 << 30, n_probes, index.n_lists, len(classes),
                           int(k), res.workspace_bytes, dim=dim,
                           class_counts=class_counts)
    return classes, class_counts, cls_ord, q_tile


def _search_ragged(index, queries, k, n_probes, filter, select_algo, res):
    """Strip-scan path (ops/strip_scan.py): work ∝ actual probed entries —
    no per-list cap, no padded-length scan, per-pair top-k fused in-kernel,
    the whole search one fused dispatch."""
    l2 = index.metric in ("sqeuclidean", "euclidean")
    # the unfiltered bias depends only on build-time state: cache it on the
    # index (one dispatch per search otherwise)
    if filter is None:
        bias = getattr(index, "_bias_cache", None)
        if bias is None:
            bias = _ragged_bias(index.list_ids, index.list_norms, None,
                                "l2" if l2 else "ip")
            try:
                # lazy caches are instance attrs OUTSIDE tree_flatten: they
                # drop on any tree_map/jit round-trip (rebuilt) and assume
                # the index is immutable-after-build — mutate list_data /
                # list_ids only through extend(), which returns a NEW index
                # (ADVICE r3)
                index._bias_cache = bias
            except AttributeError:
                pass
    else:
        bias = _ragged_bias(index.list_ids, index.list_norms, filter,
                            "l2" if l2 else "ip")
    classes, class_counts, cls_ord, q_tile = _ragged_plan_static(
        index, n_probes, k, res, index.dim)
    return _ragged_fused(
        queries, index.centers, index.list_data, bias, index.list_ids,
        cls_ord, int(k), n_probes, index.metric, select_algo,
        res.compute_dtype, classes, class_counts,
        min(q_tile, queries.shape[0]),
        jax.default_backend() != "tpu",
    )


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "q_tile", "select_algo", "compute_dtype"),
)
def _search_impl(
    queries, centers, list_data, list_ids, list_norms, filter,
    k, n_probes, metric, q_tile, select_algo, compute_dtype,
):
    # compile-ledger registration: runs at trace time only, so every
    # (re)trace of this program lands attributed (obs/compile.py)
    obs_compile.trace_event(
        "ivf_flat.search", queries=queries, centers=centers,
        list_data=list_data, list_ids=list_ids, list_norms=list_norms,
        filter=filter,
        static={"k": k, "n_probes": n_probes, "metric": metric,
                "q_tile": q_tile, "select_algo": select_algo,
                "compute_dtype": compute_dtype})
    q, dim = queries.shape
    n_lists, max_size, _ = list_data.shape
    select_min = metric != "inner_product"
    bad = jnp.float32(jnp.inf if select_min else -jnp.inf)

    # ---- stage 1: coarse quantizer (one gemm over all centers) ------------
    if metric in ("sqeuclidean", "euclidean"):
        # explicit full precision for probe ranking (ADVICE.md: backend-
        # default bf16 coarse distances can mis-rank probe lists)
        coarse = dist_mod._expanded_distance(
            queries, centers, "sqeuclidean", compute_dtype, "highest"
        )
        qn = dist_mod.sqnorm(queries)
    else:  # cosine (pre-normalized) and inner_product probe by max ip
        coarse = -dist_mod.matmul_t(queries, centers, compute_dtype, "highest")
        qn = None
    _, probes = select_k(coarse, n_probes, select_min=True, algo=select_algo)  # (q, p)

    # ---- stage 2: tiled gather + scan + final select_k --------------------
    def scan_tile(args):
        q_blk, qn_blk, probe_blk = args
        cand = list_data[probe_blk]  # (qt, p, m, d) gather
        ids = list_ids[probe_blk]  # (qt, p, m)
        ip = jnp.einsum(
            "qd,qpmd->qpm", q_blk, cand, preferred_element_type=jnp.float32
        )
        if metric in ("sqeuclidean", "euclidean"):
            norms = list_norms[probe_blk]
            d = jnp.maximum(qn_blk[:, None, None] + norms - 2.0 * ip, 0.0)
            if metric == "euclidean":
                d = jnp.sqrt(d)
        elif metric == "cosine":
            d = 1.0 - ip  # inputs are pre-normalized
        else:
            d = ip  # inner_product: ranked by max
        flat_ids = ids.reshape(ids.shape[0], -1)
        d = d.reshape(flat_ids.shape)
        valid = flat_ids >= 0
        if filter is not None:
            valid = valid & filter.test(flat_ids)
        d = jnp.where(valid, d, bad)
        vals, sel = select_k(d, k, select_min=select_min, algo=select_algo)
        out_ids = jnp.where(vals == bad, -1, jnp.take_along_axis(flat_ids, sel, axis=1))
        return vals, out_ids

    if qn is None:
        qn = jnp.zeros((q,), jnp.float32)  # unused, keeps the scan signature static
    return map_row_tiles(scan_tile, (queries, qn, probes), q_tile)


@traced("ivf_flat::search")
def search(
    index: IvfFlatIndex,
    queries,
    k: int,
    n_probes: int = 20,
    filter: Optional[Bitset] = None,
    select_algo: str = "exact",
    backend: str = "auto",
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Probe ``n_probes`` lists per query and return the top-k
    (ivf_flat-inl.cuh:516 / detail/ivf_flat_search-inl.cuh:38).

    Returns ``(distances (q,k), indices (q,k))``; indices are source row ids,
    ``-1`` where fewer than k valid candidates were found. ``filter`` excludes
    rows by id (bitset_filter analog, sample_filter.cuh:31).

    ``backend``: "ragged" (chunk-table Pallas scan, work ∝ probed entries —
    the TPU default), "gather" (jnp gather+einsum scan — the exact-fp32
    oracle path and CPU default), or "auto".
    """
    res = res or current_resources()
    queries = jnp.asarray(queries).astype(jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries must be (q, {index.dim}), got {queries.shape}")
    n_probes = int(min(n_probes, index.n_lists))
    filter_attrs = None
    if filter is not None:
        from raft_tpu.resilience import faultpoint

        faultpoint("ivf_flat.search.filter")
        # selectivity-aware widening: over-probe by ~1/pass_rate (capped)
        # so k SURVIVORS come back at selective filters — the effective
        # n_probes flows into validation, telemetry and the roofline model
        n_probes, _, f_rate, f_widen = _filtering.widen_plan(
            filter, n_probes, index.n_lists)
        filter_attrs = {"filter_pass_rate": round(f_rate, 6),
                        "filter_widen_x": round(f_widen, 4),
                        "filter_n_probes": n_probes}
    if not 0 < k <= n_probes * index.max_list_size:
        raise ValueError(
            f"k={k} out of range for n_probes={n_probes} x max_list_size={index.max_list_size}"
        )
    if index.metric == "cosine":
        queries = queries / jnp.maximum(jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-30)

    from raft_tpu.ops.strip_scan import strip_eligible

    aligned = strip_eligible(index.max_list_size) and k <= 512
    if backend == "auto":
        backend = "ragged" if jax.default_backend() == "tpu" and aligned else "gather"
    if backend not in ("ragged", "gather"):
        raise ValueError(f"unknown backend {backend!r}")
    scan_attrs = None
    if obs.enabled():
        q_obs = int(queries.shape[0])
        obs.add("ivf_flat.search.queries", q_obs)
        obs.add("ivf_flat.search.probes", q_obs * n_probes)
        # padded upper bound on candidate rows visited (the ragged backend's
        # actual work is ∝ real list fills; this is telemetry, not billing)
        obs.add("ivf_flat.search.rows_scanned",
                q_obs * n_probes * index.max_list_size)
        obs.add(f"ivf_flat.search.backend.{backend}", 1)
        scan_attrs = {"backend": backend, "queries": q_obs,
                      "probes": int(n_probes), "k": int(k)}
        if filter_attrs:
            scan_attrs.update(filter_attrs)
        # roofline note (round 15): static FLOP/byte model of this
        # dispatch, plus the strip planner's occupancy stats when the
        # host already holds the per-list lengths (the ragged path's
        # cache — telemetry must never force a device sync to get them)
        occ = None
        lens_cached = getattr(index, "_lens_np_cache", None)
        if backend == "ragged" and lens_cached is not None \
                and lens_cached.shape[0] == index.n_lists:
            from raft_tpu.ops.strip_scan import occupancy_stats
            kf_occ = min(int(k), 512)
            occ = obs_roofline.memo_occupancy(
                index,
                (id(lens_cached), q_obs, int(n_probes), kf_occ,
                 res.workspace_bytes),
                lambda: occupancy_stats(
                    lens_cached, index.max_list_size, q_obs, n_probes,
                    dim=index.dim, workspace_bytes=res.workspace_bytes,
                    kf=kf_occ))
        obs_roofline.note_dispatch(
            "ivf_flat.search",
            {"q": q_obs, "dim": index.dim, "n_lists": index.n_lists,
             "max_list_size": index.max_list_size,
             "n_probes": int(n_probes), "k": int(k),
             "dtype": str(index.list_data.dtype)},
            occupancy=occ)
    from raft_tpu.resilience import faultpoint

    faultpoint("ivf_flat.search.scan")
    # one scan-phase span either way (attrs built under the gate above so
    # the telemetry-off path stays a single branch)
    scan_span = obs.record_span("ivf_flat::scan", attrs=scan_attrs)
    if backend == "ragged":
        if not aligned:
            raise ValueError(
                f"ragged backend needs max_list_size = a power-of-two "
                f"multiple of 512, got {index.max_list_size}; rebuild with "
                "group_size=512 (or use backend='gather')"
            )
        with scan_span:
            return _search_ragged(index, queries, int(k), n_probes, filter,
                                  select_algo, res)

    # query-tile size: the (qt, p, m, d) gather is the big intermediate
    per_query = max(1, n_probes * index.max_list_size * (index.dim + 2) * 4)
    q_tile = int(max(1, min(queries.shape[0], res.workspace_bytes // per_query)))
    with scan_span:
        vals, ids = _search_impl(
            queries,
            index.centers,
            index.list_data,
            index.list_ids,
            index.list_norms,
            filter,
            int(k),
            n_probes,
            index.metric,
            q_tile,
            select_algo,
            res.compute_dtype,
        )
    return vals, ids


# ---------------------------------------------------------------------------
# Paged search (serving layer): scan a PagedListStore's vector pages
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "q_tile", "select_algo",
                     "compute_dtype"),
)
def _paged_impl(
    queries, centers, pages, page_ids, page_aux, table, filter,
    k, n_probes, metric, q_tile, select_algo, compute_dtype,
):
    """Paged-store scan: the gather-backend search (:func:`_search_impl`)
    re-shaped over (page-table, page) instead of a padded list axis. The
    per-candidate math is kept IDENTICAL (same coarse gemm, same einsum
    contraction, same bias/clamp/select sequence) so a fully-compacted
    store is bit-parity with the packed scan; the ``ids >= 0`` mask covers
    both fill-count tails and tombstones. All operand shapes derive from
    CAPACITY (page pool, table width) — appends and tombstones re-dispatch
    this same program."""
    # ledger registration (runs at trace time only): a growth retrace
    # lands attributed to the operand that grew (pages / table)
    obs_compile.trace_event(
        "ivf_flat.paged_scan", queries=queries, centers=centers,
        pages=pages, page_ids=page_ids, page_aux=page_aux, table=table,
        filter=filter,
        static={"k": k, "n_probes": n_probes, "metric": metric,
                "q_tile": q_tile, "select_algo": select_algo,
                "compute_dtype": compute_dtype})
    q, dim = queries.shape
    select_min = metric != "inner_product"
    bad = jnp.float32(jnp.inf if select_min else -jnp.inf)

    if metric in ("sqeuclidean", "euclidean"):
        coarse = dist_mod._expanded_distance(
            queries, centers, "sqeuclidean", compute_dtype, "highest"
        )
        qn = dist_mod.sqnorm(queries)
    else:
        coarse = -dist_mod.matmul_t(queries, centers, compute_dtype, "highest")
        qn = None
    _, probes = select_k(coarse, n_probes, select_min=True, algo=select_algo)

    def scan_tile(args):
        q_blk, qn_blk, probe_blk = args
        tbl = table[probe_blk]                     # (qt, p, W)
        safe = jnp.maximum(tbl, 0)
        cand = pages[safe]                          # (qt, p, W, R, d)
        ids = jnp.where(tbl[..., None] >= 0, page_ids[safe], -1)
        ip = jnp.einsum(
            "qd,qpwrd->qpwr", q_blk, cand, preferred_element_type=jnp.float32
        )
        if metric in ("sqeuclidean", "euclidean"):
            norms = page_aux[safe]
            d = jnp.maximum(qn_blk[:, None, None, None] + norms - 2.0 * ip, 0.0)
            if metric == "euclidean":
                d = jnp.sqrt(d)
        elif metric == "cosine":
            d = 1.0 - ip  # inputs are pre-normalized
        else:
            d = ip  # inner_product: ranked by max
        flat_ids = ids.reshape(ids.shape[0], -1)
        d = d.reshape(flat_ids.shape)
        valid = flat_ids >= 0
        if filter is not None:
            valid = valid & filter.test(flat_ids)
        d = jnp.where(valid, d, bad)
        vals, sel = select_k(d, k, select_min=select_min, algo=select_algo)
        out_ids = jnp.where(vals == bad, -1,
                            jnp.take_along_axis(flat_ids, sel, axis=1))
        return vals, out_ids

    if qn is None:
        qn = jnp.zeros((q,), jnp.float32)  # unused, keeps the signature static
    return map_row_tiles(scan_tile, (queries, qn, probes), q_tile)


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "select_algo",
                     "compute_dtype", "q_tile", "interpret", "impl"),
)
def _paged_fused(queries, centers, pages, bias_pool, page_ids, table,
                 chain_pages, filter, k, n_probes, metric, select_algo,
                 compute_dtype, q_tile, interpret, impl):
    """The ENTIRE paged Pallas search — coarse gemm, device strip
    planning, page-table DMA kernel, merge, finalize — as one jit (the
    ``_ragged_fused`` shape over page chains): mutable paged storage
    scanned in place at strip-kernel throughput. Every operand is
    CAPACITY-shaped, so steady-state upserts/deletes re-dispatch this
    same program (zero-recompile serving contract)."""
    from raft_tpu.ops.strip_scan import paged_strip_search_traced

    # ledger registration (trace time only): a growth retrace lands
    # attributed to the pool/table operand that grew (obs/compile.py)
    obs_compile.trace_event(
        "ivf_flat.paged_pallas", queries=queries, centers=centers,
        pages=pages, bias_pool=bias_pool, page_ids=page_ids, table=table,
        chain_pages=chain_pages, filter=filter,
        static={"k": k, "n_probes": n_probes, "metric": metric,
                "select_algo": select_algo, "compute_dtype": compute_dtype,
                "q_tile": q_tile, "interpret": interpret, "impl": impl})
    # same coarse select as the packed ragged path (parity: probe choice
    # decides the candidate set — see ivf_flat._ragged_fused's bound note)
    sa = ("packed" if select_algo == "exact" and not interpret
          and centers.shape[0] <= 4096 else select_algo)
    probes = _coarse_probes(queries, centers, n_probes, metric, sa,
                            compute_dtype)
    # the store's bias pool is already +inf at dead slots; the filter
    # masks live rows by their source id (the shared
    # _filtering.apply_filter_bias rule)
    bias = _filtering.apply_filter_bias(bias_pool, page_ids, filter)
    l2 = metric in ("sqeuclidean", "euclidean")
    vals, ids = paged_strip_search_traced(
        queries, probes, pages, bias, page_ids, table, chain_pages,
        int(k), int(k), -2.0 if l2 else -1.0, q_tile, interpret, impl=impl)
    return _finalize_ragged(vals, ids, queries, metric)


def paged_backend_auto(store, k: int) -> str:
    """Engine selection for a paged search: the Pallas page-table scan on
    TPU when the store's layout can feed it, the jnp gather scan
    otherwise (and on CPU, where gather is the exact-fp32 oracle path)."""
    from raft_tpu.ops.strip_scan import paged_eligible

    if jax.default_backend() != "tpu":
        return "gather" if store.kind != "ivf_bq" else "paged_jnp"
    if store.kind == "ivf_pq":
        row_bytes = getattr(store, "_cache_dim", 0)
    else:
        row_bytes = int(store.pages.shape[-1]) * store.pages.dtype.itemsize
    # compiled-mode DMA alignment: lane-offset bias copies want whole
    # 128-lane tiles per page (the default page height); narrower pages
    # stay on the gather path outside interpret mode
    if store.page_rows % 128 != 0:
        return "gather" if store.kind != "ivf_bq" else "paged_jnp"
    if not paged_eligible(store.table_width, store.page_rows, row_bytes,
                          int(k)):
        return "gather" if store.kind != "ivf_bq" else "paged_jnp"
    return "paged_pallas"


def _paged_plan_static(store, n_probes: int, k: int, res, dim: int):
    """Query-tile sizing for the paged strip engines — the
    ``_ragged_plan_static`` twin over the capacity layout (one length
    class, ``class_counts = (n_lists,)``)."""
    from raft_tpu.ops import strip_scan as ss

    return ss.fit_q_tile(1 << 30, n_probes, store.n_lists, 1, int(k),
                         res.workspace_bytes, dim=dim,
                         class_counts=(store.n_lists,))


@traced("ivf_flat::search_paged")
def search_paged(
    store,
    queries,
    k: int,
    n_probes: int = 20,
    filter: Optional[Bitset] = None,
    select_algo: str = "exact",
    backend: str = "auto",
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """k-NN over a mutable paged vector store
    (:class:`raft_tpu.serving.PagedListStore`, kind ``"ivf_flat"``): same
    contract as :func:`search`, but the store keeps serving while rows
    stream in/out — no repack, and steady-state mutations never recompile
    this scan (its shapes depend only on store capacity).

    ``backend``: "paged_pallas" (page-table DMA strip kernel — the TPU
    engine, interpret-mode elsewhere), "paged_jnp" (its pure-jnp
    bit-parity reference), "gather" (jnp gather scan — the exact-fp32
    oracle, CPU default), or "auto"."""
    if store.kind != "ivf_flat":
        raise ValueError(f"expected an ivf_flat store, got {store.kind!r}")
    res = res or current_resources()
    queries = jnp.asarray(queries).astype(jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != store.dim:
        raise ValueError(f"queries must be (q, {store.dim}), got {queries.shape}")
    n_probes = int(min(n_probes, store.n_lists))
    if filter is None:
        # a standing store-level filter (PagedListStore.set_filter) applies
        # when the caller passes none — per-call filters take precedence
        filter = getattr(store, "filter", None)
    filter_attrs = None
    if filter is not None:
        from raft_tpu.resilience import faultpoint

        faultpoint("ivf_flat.search.filter")
        n_probes, _, f_rate, f_widen = _filtering.widen_plan(
            filter, n_probes, store.n_lists)
        filter_attrs = {"filter_pass_rate": round(f_rate, 6),
                        "filter_widen_x": round(f_widen, 4),
                        "filter_n_probes": n_probes}
    if backend == "auto":
        backend = paged_backend_auto(store, k)
    if backend not in ("gather", "paged_pallas", "paged_jnp"):
        raise ValueError(f"unknown backend {backend!r}")
    # one ATOMIC store snapshot: pool/table read separately could tear
    # against a concurrent upsert's capacity growth
    if backend == "gather":
        pages, page_ids, page_aux, table = store.scan_state()
    else:
        pages, bias_pool, _, page_ids, table, chain_pages = \
            store.paged_scan_state()
    width = int(table.shape[1])
    if not 0 < k <= n_probes * width * store.page_rows:
        raise ValueError(f"k={k} out of range")
    if store.metric == "cosine":
        queries = queries / jnp.maximum(
            jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-30)
    scan_attrs = None
    if obs.enabled():
        q_obs = int(queries.shape[0])
        obs.add("ivf_flat.search_paged.queries", q_obs)
        obs.add("ivf_flat.search_paged.probes", q_obs * n_probes)
        obs.add(f"ivf_flat.search_paged.backend.{backend}", 1)
        scan_attrs = {"backend": backend, "queries": q_obs,
                      "probes": int(n_probes), "k": int(k),
                      "table_width": width}
        if filter_attrs:
            scan_attrs.update(filter_attrs)
        if backend == "gather":
            # roofline note (round 15): the gather scan's per-(query,
            # probe) capacity-padded chain cost — no cross-query sharing,
            # which is exactly what the paged Pallas engine buys back
            obs_roofline.note_dispatch(
                "ivf_flat.paged_scan",
                {"q": q_obs, "dim": store.dim, "n_lists": store.n_lists,
                 "page_rows": store.page_rows, "table_width": width,
                 "n_probes": int(n_probes), "k": int(k),
                 "dtype": str(pages.dtype)})
        else:
            # paged-Pallas roofline + planner occupancy (round-15 standing
            # gate: new hot-path kernels ship with their model). The
            # planner stats come from host state the store already holds —
            # no device sync (memoized until the layout/fill moves).
            from raft_tpu.ops.strip_scan import paged_occupancy_stats
            row_bytes = int(pages.shape[-1]) * pages.dtype.itemsize
            occ = obs_roofline.memo_occupancy(
                store,
                (store.pages_used, store.size, store.tombstones, width,
                 q_obs, int(n_probes), int(k), res.workspace_bytes),
                lambda: paged_occupancy_stats(
                    width, store.page_rows, store._list_pages, store.size,
                    store.tombstones, q_obs, int(n_probes), int(k),
                    row_bytes, workspace_bytes=res.workspace_bytes,
                    dim=store.dim))
            obs_roofline.note_dispatch(
                "ivf_flat.paged_pallas",
                {"q": q_obs, "dim": store.dim, "n_lists": store.n_lists,
                 "page_rows": store.page_rows, "table_width": width,
                 "n_probes": int(n_probes), "k": int(k),
                 "dtype": str(pages.dtype)},
                occupancy=occ)
    from raft_tpu.resilience import faultpoint

    if backend != "gather":
        interpret = jax.default_backend() != "tpu"
        q_tile = min(_paged_plan_static(store, n_probes, k, res, store.dim),
                     queries.shape[0])
        impl = "pallas" if backend == "paged_pallas" else "jnp"
        faultpoint("ivf_flat.search_paged.scan")
        with obs.record_span("ivf_flat::paged_pallas", attrs=scan_attrs):
            with obs_compile.watch():
                return _paged_fused(
                    queries, store.centers, pages, bias_pool, page_ids,
                    table, chain_pages, filter, int(k), n_probes,
                    store.metric, select_algo, res.compute_dtype,
                    int(q_tile), interpret, impl)
    # the (qt, p, W, R, d) page gather is the big intermediate
    per_query = max(1, n_probes * width * store.page_rows * (store.dim + 2) * 4)
    q_tile = int(max(1, min(queries.shape[0],
                            res.workspace_bytes // per_query)))
    faultpoint("ivf_flat.search_paged.scan")
    with obs.record_span("ivf_flat::paged_scan", attrs=scan_attrs):
        # ledger watch: a dispatch that (re)traces gets its wall-clock
        # stamped onto the ledger record (steady state stamps nothing)
        with obs_compile.watch():
            return _paged_impl(
                queries, store.centers, pages, page_ids, page_aux, table,
                filter, int(k), n_probes, store.metric,
                q_tile, select_algo, res.compute_dtype,
            )


def split_list_rows(rows, n_iter: int = 8):
    """Deterministic 2-means split of one overfull list's rows — the
    maintenance re-cluster's hot-list splitter (serving/maintenance.py).

    Seeding is data-derived (the two extreme rows along the max-variance
    coordinate) and Lloyd runs a few rounds on the host: the input is one
    list (thousands of rows at most), so there is nothing worth
    dispatching, and no RNG keeps the split reproducible across runs —
    the same no-clock/no-global-RNG determinism contract as the shadow
    sampler's hashing.

    Returns ``(centers (2, dim) float32, assign (n,) int32)``. Degenerate
    inputs (all rows identical) collapse onto one side; callers skip the
    split when ``assign`` is constant.
    """
    rows = np.asarray(rows, np.float32)
    if rows.ndim != 2 or rows.shape[0] < 2:
        raise ValueError("split_list_rows needs a (n >= 2, dim) row matrix")
    mu = rows.mean(axis=0)
    coord = rows[:, int(((rows - mu) ** 2).mean(axis=0).argmax())]
    centers = np.stack([rows[int(coord.argmin())], rows[int(coord.argmax())]])
    assign = np.zeros(rows.shape[0], np.int32)
    for it in range(max(1, int(n_iter))):
        d0 = ((rows - centers[0]) ** 2).sum(axis=1)
        d1 = ((rows - centers[1]) ** 2).sum(axis=1)
        new = (d1 < d0).astype(np.int32)
        if it > 0 and np.array_equal(new, assign):
            break
        assign = new
        for side in (0, 1):
            sel = rows[assign == side]
            if sel.shape[0]:
                centers[side] = sel.mean(axis=0)
    return centers, assign
