"""Out-of-core brute-force kNN: host-staged dataset streaming + the lazy
batch-k query iterator (reference
neighbors/detail/knn_brute_force_batch_k_query.cuh, brute_force_types.hpp
batch_k_query — the scale axis for >HBM datasets like wiki-all 88M×768,
docs/source/wiki_all_dataset.md:3).

TPU design:
  * `search_out_of_core` — the dataset stays HOST-resident (any numpy-like,
    incl. np.memmap); row chunks stream through `jax.device_put` and each
    chunk's exact top-k merges into a running result. XLA's async dispatch
    overlaps chunk i+1's transfer with chunk i's gemm (the reference's
    stream/copy overlap). HBM holds one chunk + the (q, k) running state,
    never the dataset.
  * `BatchKQuery` — iterator yielding each query's neighbors in slabs of
    `batch_size` (ranks [0, b), [b, 2b), …), matching the reference's
    prefetch-iterator contract: downstream consumers (e.g. HDBSCAN-style
    algorithms) pull until satisfied. Each pull re-selects with a larger k
    over cached norms — the same "just run knn with offset+batch" strategy
    the GPU implementation uses.
"""

from __future__ import annotations

import functools
from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.trace import traced
from raft_tpu.ops import distance as dist_mod
from raft_tpu.ops.select_k import select_k

SUPPORTED_METRICS = ("sqeuclidean", "euclidean", "inner_product", "cosine")


@functools.partial(jax.jit, static_argnames=("k", "metric", "select_algo"))
def _chunk_topk(queries, qn, chunk, chunk_norms, row0, k: int, metric: str,
                select_algo: str):
    """Exact top-k of one device-resident chunk (ids offset by row0)."""
    ip = dist_mod.matmul_t(queries, chunk, None, "highest")
    if metric in ("sqeuclidean", "euclidean"):
        d = jnp.maximum(qn[:, None] + chunk_norms[None, :] - 2.0 * ip, 0.0)
    elif metric == "cosine":
        d = 1.0 - ip  # operands pre-normalized
    else:
        d = -ip  # inner_product ranked by max
    vals, ids = select_k(d, min(k, chunk.shape[0]), algo=select_algo)
    return vals, ids + row0


@functools.partial(jax.jit, static_argnames=("k",))
def _merge_running(best_v, best_i, vals, ids, k: int):
    allv = jnp.concatenate([best_v, vals], axis=1)
    alli = jnp.concatenate([best_i, ids], axis=1)
    v, sel = jax.lax.top_k(-allv, k)
    return -v, jnp.take_along_axis(alli, sel, axis=1)


@functools.partial(jax.jit, static_argnames=("k", "chunk_rows", "metric"))
def _search_device_chunked_impl(dataset, queries, k: int, chunk_rows: int,
                                metric: str):
    """One-dispatch chunked scan (see :func:`search_device_chunked`)."""
    metric = dist_mod.canonical_metric(metric)
    if metric not in SUPPORTED_METRICS:
        raise ValueError(
            f"supported metrics {SUPPORTED_METRICS}, got {metric!r}")
    n, dim = dataset.shape
    q = queries.shape[0]
    chunk_rows = min(chunk_rows, n)
    queries = queries.astype(jnp.float32)
    if metric == "cosine":
        queries = queries / jnp.maximum(
            jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-30)
    qn = dist_mod.sqnorm(queries)
    n_chunks = -(-n // chunk_rows)
    inf = jnp.float32(jnp.inf)

    def body(c, carry):
        best_v, best_i = carry
        # dynamic_slice clamps an out-of-range start: mirror the clamp so
        # the tail chunk's ids match the rows actually sliced (the last
        # chunk re-scans some rows — duplicates merge away exactly)
        start = jnp.minimum(c * chunk_rows, max(n - chunk_rows, 0))
        chunk = lax.dynamic_slice(
            dataset, (start, 0), (chunk_rows, dim)).astype(jnp.float32)
        rows = start + jnp.arange(chunk_rows, dtype=jnp.int32)
        if metric == "cosine":
            chunk = chunk / jnp.maximum(
                jnp.linalg.norm(chunk, axis=1, keepdims=True), 1e-30)
        ip = jnp.einsum("qd,cd->qc", queries, chunk,
                        preferred_element_type=jnp.float32)
        if metric == "inner_product":
            d = -ip
        elif metric == "cosine":
            d = 1.0 - ip
        else:
            cn = jnp.sum(chunk * chunk, axis=1)
            d = jnp.maximum(qn[:, None] + cn[None, :] - 2.0 * ip, 0.0)
        # tail-chunk overlap rows were already scanned: mask them so no id
        # can enter the running top-k twice
        d = jnp.where((rows >= c * chunk_rows)[None, :], d, inf)
        from raft_tpu.ops.select_k import iter_topk_min

        vals, sel = iter_topk_min(d, k)
        ids = jnp.where(jnp.isinf(vals), -1, rows[sel])
        return _merge_running(best_v, best_i, vals, ids, k)

    best_v = jnp.full((q, k), inf, jnp.float32)
    best_i = jnp.full((q, k), -1, jnp.int32)
    best_v, best_i = lax.fori_loop(0, n_chunks, body, (best_v, best_i))
    if metric == "inner_product":
        best_v = jnp.where(best_i >= 0, -best_v, -inf)
    elif metric == "euclidean":
        best_v = jnp.where(best_i >= 0, jnp.sqrt(best_v), inf)
    return best_v, best_i


@traced("batch_knn::search_device_chunked")
def search_device_chunked(dataset, queries, k: int,
                          chunk_rows: int = 131072,
                          metric: str = "sqeuclidean"):
    """Exact kNN over a DEVICE-resident dataset too large for one (q, n)
    score matrix (e.g. 10M rows: the full fp32 block would be tens of GB).

    One dispatch: a ``fori_loop`` slides a (chunk_rows, dim) window over
    the dataset, each step one MXU gemm + an exact iterative top-k merged
    into the running (q, k) state. The complement of ``search_out_of_core``
    (host-resident streaming) for datasets that fit HBM but whose score
    matrix does not. Returns (distances (q, k), indices (q, k)).

    OOM-adaptive (ISSUE 3): ``chunk_rows`` sizes the resident
    (chunk + (q, chunk) score block) workspace; a ``RESOURCE_EXHAUSTED``
    failure re-dispatches at half the chunk size down to a floor
    (``resilience.degrade_on_oom``), recording ``resilience.degraded_tile``
    — the round-4 deep10m OOM class recovers instead of sinking the
    section."""
    from raft_tpu.resilience import degrade_on_oom, faultpoint

    chunk_rows = min(int(chunk_rows), dataset.shape[0])

    def attempt(rows):
        faultpoint("batch_knn.search_device_chunked")
        return _search_device_chunked_impl(dataset, queries, int(k),
                                           int(rows), metric)

    floor = min(chunk_rows, max(int(k), 128))
    return degrade_on_oom(attempt, chunk_rows, floor=floor,
                          site="batch_knn.search_device_chunked")


@traced("batch_knn::search_out_of_core")
def search_out_of_core(
    dataset,
    queries,
    k: int,
    metric: str = "sqeuclidean",
    chunk_rows: int = 0,
    select_algo: str = "exact",
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN over a host-resident dataset streamed in row chunks.

    ``dataset``: (n, dim) numpy-like on HOST (np.memmap works); it is never
    materialized on device. Returns (distances (q, k), indices (q, k)).
    """
    res = res or current_resources()
    metric = dist_mod.canonical_metric(metric)
    if metric not in SUPPORTED_METRICS:
        raise ValueError(f"supported metrics {SUPPORTED_METRICS}, got {metric!r}")
    n, dim = dataset.shape
    queries = jnp.asarray(queries).astype(jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != dim:
        raise ValueError(f"queries must be (q, {dim}), got {queries.shape}")
    if not 0 < k <= n:
        raise ValueError(f"k={k} out of range for {n} rows")
    if metric == "cosine":
        queries = queries / jnp.maximum(
            jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-30)

    if chunk_rows <= 0:
        # chunk budget: the chunk itself + its (q, chunk) distance block
        q = queries.shape[0]
        chunk_rows = int(max(k, min(n, res.workspace_bytes // max(1, (dim + q) * 4))))
    qn = dist_mod.sqnorm(queries)

    from raft_tpu.core.interruptible import check_interrupt
    from raft_tpu.resilience import (active_deadline, degrade_on_oom,
                                     faultpoint)

    def scan(chunk_rows):
        # the whole host loop is the degradation unit: an OOM mid-stream
        # restarts the scan at half the chunk size (state is per-scan, so
        # a restart is exact); an expired Deadline breaks AFTER at least
        # one chunk and marks the scope degraded — the running top-k over
        # the scanned prefix is the partial result
        best_v = jnp.full((queries.shape[0], k),
                          jnp.inf, jnp.float32)
        best_i = jnp.full((queries.shape[0], k), -1, jnp.int32)
        for s in range(0, n, chunk_rows):
            dl = active_deadline()
            if dl is not None and s > 0 and dl.reached():
                dl.mark_degraded("batch_knn.search_out_of_core")
                break
            check_interrupt()
            faultpoint("batch_knn.search_out_of_core.chunk")
            host_chunk = np.asarray(dataset[s:s + chunk_rows], dtype=np.float32)
            chunk = jax.device_put(host_chunk)
            if metric == "cosine":
                chunk = chunk / jnp.maximum(
                    jnp.linalg.norm(chunk, axis=1, keepdims=True), 1e-30)
            cn = dist_mod.sqnorm(chunk)
            vals, ids = _chunk_topk(queries, qn, chunk, cn, s, int(k), metric,
                                    select_algo)
            if vals.shape[1] < k:  # short final chunk: pad before the merge
                pad = k - vals.shape[1]
                vals = jnp.pad(vals, ((0, 0), (0, pad)), constant_values=jnp.inf)
                ids = jnp.pad(ids, ((0, 0), (0, pad)), constant_values=-1)
            best_v, best_i = _merge_running(best_v, best_i, vals, ids, int(k))
        return best_v, best_i

    best_v, best_i = degrade_on_oom(
        scan, chunk_rows, floor=min(int(chunk_rows), max(int(k), 128)),
        site="batch_knn.search_out_of_core")

    if metric == "euclidean":
        best_v = jnp.sqrt(jnp.maximum(best_v, 0.0))
    elif metric == "inner_product":
        best_v = jnp.where(best_i >= 0, -best_v, -jnp.inf)
        return best_v, best_i
    best_v = jnp.where(best_i >= 0, best_v, jnp.inf)
    return best_v, best_i


class BatchKQuery:
    """Lazy neighbor-slab iterator (batch_k_query analog,
    brute_force_types.hpp / knn_brute_force_batch_k_query.cuh).

    Iterating yields ``(distances (q, b), indices (q, b))`` for neighbor
    ranks [0, b), then [b, 2b), … up to the index size. Query norms and the
    device dataset are computed once and reused across pulls.
    """

    def __init__(self, index, queries, batch_size: int,
                 res: Optional[Resources] = None):
        from raft_tpu.neighbors import brute_force

        self._bf = brute_force
        self.index = index
        self.queries = jnp.asarray(queries)
        self.batch_size = int(batch_size)
        if self.batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        self.res = res or current_resources()
        self._cached_k = 0
        self._vals = None
        self._ids = None

    def _ensure(self, upto: int) -> None:
        upto = min(upto, self.index.size)
        if upto <= self._cached_k:
            return
        # re-select at the larger k (the reference recomputes per batch the
        # same way; distances are cached only through the gemm engine)
        self._vals, self._ids = self._bf.search(
            self.index, self.queries, upto, res=self.res)
        self._cached_k = upto

    def __iter__(self) -> Iterator[Tuple[jax.Array, jax.Array]]:
        offset = 0
        n = self.index.size
        while offset < n:
            b = min(self.batch_size, n - offset)
            self._ensure(offset + b)
            yield (self._vals[:, offset:offset + b],
                   self._ids[:, offset:offset + b])
            offset += b
