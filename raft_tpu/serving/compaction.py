"""Background compaction: tombstone-ratio-triggered, off the hot path.

Deletes tombstone in place (serving/store.py) — the slots stay dead until
:meth:`~raft_tpu.serving.PagedListStore.compact` folds the live rows back
together. Left alone, a delete-heavy serving window accumulates dead
slots the paged scans still DMA past (``tombstone_fraction`` in the paged
occupancy stats) and the page pool's free list starves into growth
retraces. The :class:`CompactionManager` closes the loop: when
``tombstones / live_rows`` crosses ``RAFT_TPU_SERVING_COMPACT_RATIO`` it
runs one compaction CYCLE —

1. ``store.compact()`` — fold the live rows into the packed layout
   (only the row snapshot holds the store lock; the fold runs on
   immutable array snapshots, so serving traffic is never stalled);
2. ``store.compact_swap(packed, v0)`` — re-page at the SAME capacity and
   table width and swap atomically, validated against the
   ``mutation_version`` observed before the fold: a mutation that landed
   mid-cycle aborts the swap (classified ``stale``, retried on the next
   pump) instead of being lost. In-flight ``QueryQueue`` dispatches hold
   their own array snapshots and are untouched either way; capacity is
   unchanged, so the paged scans re-dispatch their compiled programs —
   compaction never recompiles the data plane.

The cycle is deadline-bounded (``RAFT_TPU_SERVING_COMPACT_DEADLINE_S``,
:class:`raft_tpu.resilience.Deadline`), faultpointed
(``serving.compact.run`` — the round-7 standing gate; tier-1 arms
oom/fatal/delay and asserts the classified recovery), and every failure
routes through ``resilience.classify`` into counters + the event ring.

Drive it deterministically (:meth:`CompactionManager.pump` in the serving
loop's idle gaps — what the bench and tier-1 do) or with the background
worker (:meth:`start` / :meth:`stop`).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from raft_tpu import obs, resilience
from raft_tpu.resilience.retry import record_event

COMPACT_RATIO_ENV = "RAFT_TPU_SERVING_COMPACT_RATIO"
COMPACT_DEADLINE_ENV = "RAFT_TPU_SERVING_COMPACT_DEADLINE_S"
COMPACT_INTERVAL_ENV = "RAFT_TPU_SERVING_COMPACT_INTERVAL_S"

_DEFAULT_RATIO = 0.25
_DEFAULT_DEADLINE_S = 30.0
_DEFAULT_INTERVAL_S = 0.5


def _env_float(env: str, default: float) -> float:
    raw = os.environ.get(env, "").strip()
    if not raw:
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def default_compact_ratio() -> float:
    """Trigger threshold on ``tombstones / live_rows``
    (``RAFT_TPU_SERVING_COMPACT_RATIO``, default 0.25)."""
    return _env_float(COMPACT_RATIO_ENV, _DEFAULT_RATIO)


def default_compact_deadline() -> float:
    """Per-cycle wall-clock bound in seconds
    (``RAFT_TPU_SERVING_COMPACT_DEADLINE_S``, default 30)."""
    return _env_float(COMPACT_DEADLINE_ENV, _DEFAULT_DEADLINE_S)


class CompactionManager:
    """Tombstone-ratio-triggered compaction driver for one store.

    ``ratio``/``deadline_s`` default from the env knobs;
    ``min_tombstones`` keeps tiny stores from compacting on their first
    delete. Thread-safe against the store's own locking; only one cycle
    runs at a time (``pump`` from two threads serializes on ``_busy``).
    """

    def __init__(self, store, *, ratio: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 min_tombstones: int = 1,
                 interval_s: Optional[float] = None):
        self.store = store
        self.ratio = float(ratio if ratio is not None
                           else default_compact_ratio())
        self.deadline_s = float(deadline_s if deadline_s is not None
                                else default_compact_deadline())
        self.min_tombstones = int(min_tombstones)
        self.interval_s = float(interval_s if interval_s is not None
                                else _env_float(COMPACT_INTERVAL_ENV,
                                                _DEFAULT_INTERVAL_S))
        # counter plane: mutated by whichever thread wins _busy (and by
        # should_compact from ANY caller), read by stats() from serving
        # threads — its own leaf lock, never held across store calls
        self._stats_lock = threading.Lock()
        self.cycles = 0          # guarded-by: _stats_lock, reads-ok
        self.stale_swaps = 0     # guarded-by: _stats_lock, reads-ok
        self.failures = 0        # guarded-by: _stats_lock, reads-ok
        self.last_status: Optional[str] = None      # guarded-by: _stats_lock, reads-ok
        self.last_duration_s: Optional[float] = None  # guarded-by: _stats_lock, reads-ok
        self.tombstone_ratio_peak = 0.0  # guarded-by: _stats_lock, reads-ok
        self._busy = threading.Lock()
        self._worker: Optional[threading.Thread] = None
        self._stopping = False

    # -- policy -------------------------------------------------------------
    def should_compact(self) -> bool:
        """True when the store's tombstone load crosses the trigger."""
        ratio = self.store.tombstone_ratio
        with self._stats_lock:
            if ratio > self.tombstone_ratio_peak:
                self.tombstone_ratio_peak = ratio
        return (self.store.tombstones >= self.min_tombstones
                and ratio > self.ratio)

    # -- one cycle ----------------------------------------------------------
    def pump(self) -> Optional[dict]:
        """One scheduler step: run a compaction cycle if the trigger
        fires (and no other cycle is in flight). Returns the cycle's
        status dict, or None when there was nothing to do — the
        deterministic driver for serving loops and tier-1 tests."""
        if not self.should_compact():
            return None
        if not self._busy.acquire(blocking=False):
            return None  # another thread's cycle is in flight
        try:
            return self._cycle()
        finally:
            self._busy.release()

    def _cycle(self) -> dict:
        store = self.store
        t0 = time.perf_counter()
        v0 = store.mutation_version
        tombstones0 = store.tombstones
        attrs = ({"tombstones": tombstones0, "version": v0}
                 if obs.enabled() else None)
        try:
            with obs.record_span("serving::compact_cycle", attrs=attrs):
                with resilience.Deadline(self.deadline_s,
                                         label="serving.compact"):
                    # faultpoint INSIDE the deadline scope: an armed hang
                    # spins on check_interrupt and must be bounded by
                    # deadline_s, not the fault's own safety cap
                    resilience.faultpoint("serving.compact.run")
                    packed = store.compact()
                    swapped = store.compact_swap(packed, v0)
        except Exception as e:
            kind = resilience.classify(e)
            with self._stats_lock:
                self.failures += 1
                self.last_status = kind
                self.last_duration_s = time.perf_counter() - t0
            obs.add(f"serving.compact.{kind.lower()}")
            record_event("serving_compact_error", kind=kind,
                         tombstones=tombstones0, error=repr(e)[:200])
            return {"status": kind, "tombstones": tombstones0,
                    "duration_s": self.last_duration_s}
        dt = time.perf_counter() - t0
        if not swapped:
            # a mutation landed between the snapshot and the swap: the
            # cycle's work is discarded, nothing changed, the next pump
            # retries against the new version — classified, never silent
            with self._stats_lock:
                self.last_duration_s = dt
                self.stale_swaps += 1
                self.last_status = "stale"
            obs.add("serving.compact.stale")
            record_event("serving_compact_stale", tombstones=tombstones0,
                         version=v0)
            return {"status": "stale", "tombstones": tombstones0,
                    "duration_s": dt}
        with self._stats_lock:
            self.last_duration_s = dt
            self.cycles += 1
            self.last_status = "ok"
        if obs.enabled():
            obs.add("serving.compact.cycles")
            obs.observe("serving.compact.duration_s", dt)
            obs.add("serving.compact.reclaimed_rows", tombstones0)
        return {"status": "ok", "reclaimed": tombstones0,
                "duration_s": dt}

    # -- worker -------------------------------------------------------------
    def start(self) -> None:
        """Run the trigger check on a daemon worker thread — compaction
        truly off the serving thread (the bench's pump-in-idle-gaps mode
        stays available for deterministic runs)."""
        if self._worker is not None and self._worker.is_alive():
            return
        self._stopping = False
        self._worker = threading.Thread(
            target=self._run_loop, name="raft-tpu-compaction", daemon=True)
        self._worker.start()

    def _run_loop(self) -> None:
        stale_streak = 0
        while not self._stopping:
            out = self.pump()
            if out is not None and out.get("status") == "stale":
                # ONE immediate retry (the trigger still holds and the
                # race was probably transient) — but a store mutating
                # faster than a fold completes would otherwise livelock
                # this thread into back-to-back discarded folds, so
                # repeated staleness backs off to the poll interval
                stale_streak += 1
                if stale_streak <= 1:
                    continue
            else:
                stale_streak = 0
            time.sleep(self.interval_s)

    def stop(self, timeout: float = 30.0) -> None:
        self._stopping = True
        if self._worker is not None:
            self._worker.join(timeout=timeout)
            self._worker = None

    def stats(self) -> dict:
        ratio = self.store.tombstone_ratio  # store call OUTSIDE the lock
        with self._stats_lock:
            return {
                "cycles": self.cycles,
                "stale_swaps": self.stale_swaps,
                "failures": self.failures,
                "last_status": self.last_status,
                "last_duration_s": self.last_duration_s,
                "tombstone_ratio": ratio,
                "tombstone_ratio_peak": round(self.tombstone_ratio_peak, 4),
                "ratio_threshold": self.ratio,
                "deadline_s": self.deadline_s,
            }
