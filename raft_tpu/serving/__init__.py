"""Serving layer: paged mutable IVF storage + SLO-aware dynamic batching.

The subsystem that turns the repo's build-once/search-once bench shape
into a system that serves streaming traffic (ROADMAP item 2): a
:class:`PagedListStore` gives ivf_flat / ivf_pq / ivf_bq indexes an
online mutable storage layout — fixed-size pages per list, appended on
:meth:`~PagedListStore.upsert`, tombstoned on
:meth:`~PagedListStore.delete`, scanned without recompile (the paged
Pallas strip engines on TPU, the jnp gather scans elsewhere), folded
back to the packed snapshot layout by :meth:`~PagedListStore.compact` —
a :class:`QueryQueue` coalesces one-at-a-time requests with per-request
deadlines into dynamically sized device batches under a latency SLO, and
a :class:`CompactionManager` reclaims tombstones off the hot path when
the tombstone ratio crosses ``RAFT_TPU_SERVING_COMPACT_RATIO``, and a
:class:`MaintenanceManager` generalizes it into the always-live index
loop: drift detection (fill skew + tombstones + shadow recall trend) and
incremental online re-clustering (split hot lists / merge cold ones,
re-encode only the affected rows, swap atomically — zero recompiles).

Usage::

    from raft_tpu import serving
    from raft_tpu.neighbors import ivf_flat

    index = ivf_flat.build(dataset, ivf_flat.IvfFlatParams(n_lists=1024))
    store = serving.PagedListStore.from_index(index)
    store.upsert(new_vectors, new_ids)          # appends to tail pages
    store.delete(stale_ids)                     # tombstones in place
    vals, ids = serving.search(store, queries, k=10, n_probes=32)

    queue = serving.QueryQueue(serving.searcher(store, k=10, n_probes=32),
                               slo_s=0.05)
    queue.start()
    handle = queue.submit(one_query, timeout_s=0.2)
    vals, ids = handle.result()
    snapshot = store.compact()                  # packed index, v2-serializable
"""

from raft_tpu import obs
from raft_tpu.core.trace import traced
from raft_tpu.neighbors import _packing
from raft_tpu.neighbors import ivf_bq as _ivf_bq
from raft_tpu.neighbors import ivf_flat as _ivf_flat
from raft_tpu.neighbors import ivf_pq as _ivf_pq
from raft_tpu.serving.batching import QueryQueue, RequestHandle
from raft_tpu.serving.capacity import (
    COLD,
    HOT,
    MAX_DEMOTIONS_ENV,
    PROMOTE_DEADLINE_ENV,
    WARM,
    WINDOW_ENV,
    CapacityController,
    CapacityRejected,
    TenantRegistry,
    TenantResult,
)
from raft_tpu.serving.controller import (
    CONTROL_INTERVAL_ENV,
    COOL_WINDOWS_ENV,
    MAX_ACTIONS_ENV,
    BurnRateController,
    KnobActuator,
    default_control_interval,
    default_cool_windows,
    default_max_actions,
)
from raft_tpu.serving.compaction import (
    COMPACT_DEADLINE_ENV,
    COMPACT_INTERVAL_ENV,
    COMPACT_RATIO_ENV,
    CompactionManager,
    default_compact_deadline,
    default_compact_ratio,
)
from raft_tpu.serving.maintenance import (
    MAINT_DEADLINE_ENV,
    MAINT_DRIFT_ENV,
    MAINT_INTERVAL_ENV,
    MAINT_PAIRS_ENV,
    MAINT_SKEW_ENV,
    MaintenanceManager,
    default_drift_threshold,
    default_maintenance_deadline,
    default_maintenance_interval,
    default_max_pairs,
    default_split_skew,
)
from raft_tpu.serving.store import (
    PAGE_ROWS_ENV,
    PagedListStore,
    default_page_rows,
)

_FAMILY = {"ivf_flat": _ivf_flat, "ivf_pq": _ivf_pq, "ivf_bq": _ivf_bq}


@traced("serving::search")
def search(store: PagedListStore, queries, k: int, n_probes: int = 20,
           **kwargs):
    """Search a paged store through its kind's paged scan path
    (``ivf_flat.search_paged`` / ``ivf_pq.search_paged`` /
    ``ivf_bq.search_paged``)."""
    if obs.enabled():
        obs.add("serving.searches")
    return _FAMILY[store.kind].search_paged(store, queries, k,
                                            n_probes=n_probes, **kwargs)


def paged_engine(store: PagedListStore, k: int) -> str:
    """The engine ``backend="auto"`` resolves to for this store/k on the
    current jax backend — what the bench stamps as ``paged_engine``."""
    return _ivf_flat.paged_backend_auto(store, k)


def searcher(store: PagedListStore, k: int, n_probes: int = 20, **kwargs):
    """A ``search_fn`` for :class:`QueryQueue`, closed over one store and
    one search configuration."""

    def run(queries):
        return search(store, queries, k, n_probes=n_probes, **kwargs)

    return run


def scan_trace_count() -> int:
    """Total (re)traces of the paged scan programs in this process — a
    thin shim over the compile ledger (`obs/compile.py`; every paged
    backend records a ledger trace_event at trace time). The
    zero-recompile serving contract is asserted on deltas of this counter,
    and each retrace additionally carries its operand shape-diff in the
    ledger, so a nonzero delta names the operand that grew."""
    return _packing.paged_trace_count()


__all__ = [
    "BurnRateController",
    "COLD",
    "COMPACT_DEADLINE_ENV",
    "COMPACT_INTERVAL_ENV",
    "COMPACT_RATIO_ENV",
    "CONTROL_INTERVAL_ENV",
    "COOL_WINDOWS_ENV",
    "CapacityController",
    "CapacityRejected",
    "CompactionManager",
    "HOT",
    "KnobActuator",
    "MAX_ACTIONS_ENV",
    "MAINT_DEADLINE_ENV",
    "MAINT_DRIFT_ENV",
    "MAINT_INTERVAL_ENV",
    "MAINT_PAIRS_ENV",
    "MAINT_SKEW_ENV",
    "MAX_DEMOTIONS_ENV",
    "MaintenanceManager",
    "PAGE_ROWS_ENV",
    "PROMOTE_DEADLINE_ENV",
    "PagedListStore",
    "QueryQueue",
    "RequestHandle",
    "TenantRegistry",
    "TenantResult",
    "WARM",
    "WINDOW_ENV",
    "default_compact_deadline",
    "default_compact_ratio",
    "default_control_interval",
    "default_cool_windows",
    "default_drift_threshold",
    "default_max_actions",
    "default_maintenance_deadline",
    "default_maintenance_interval",
    "default_max_pairs",
    "default_page_rows",
    "default_split_skew",
    "paged_engine",
    "scan_trace_count",
    "search",
    "searcher",
]
