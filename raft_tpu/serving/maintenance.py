"""Always-live index maintenance: drift detection + online re-clustering.

A paged store under sustained upserts decays in three distinct ways, and
until now only one of them had a background answer:

* **tombstones** — dead slots the scans DMA past; compaction
  (serving/compaction.py) already folds them out.
* **list skew** — a drifting data distribution overfills some lists: the
  padded scans pay the longest chain, and recall at fixed ``n_probes``
  drops because one probe no longer means one n-th of the corpus.
* **centroid staleness** — the coarse quantizer was trained on the
  corpus of round 0; recall decays *silently* as the corpus walks away
  from it. The shadow sampler (obs/shadow.py) can SEE this — its Wilson
  interval is the statistical band the live estimate should stay in —
  but nothing acted on it.

The :class:`MaintenanceManager` generalizes the compaction pattern into a
maintenance plane with three deadline-bounded, faultpointed phases:

1. **detect** (``serving.maintenance.detect``) — fold per-list fill skew
   (the store's incremental ``_list_live`` counters), tombstone ratio and
   the shadow sampler's recall trend into one ``drift_score`` (each
   component normalized by its own trigger threshold, so 1.0 means "some
   signal crossed its line"). Exported as the ``store.list_skew`` /
   ``store.drift_score`` gauges plus a classified ``drift_detected``
   event naming the dominant signal.
2. **recluster** (``serving.maintenance.recluster``) — split the hottest
   lists (deterministic 2-means, ivf_flat.split_list_rows) into their own
   slot plus a cold donor's, re-assign the donor's rows to their nearest
   new center, and re-encode ONLY the affected rows through the shared
   streamed-build fast path (``_prepare_payload`` → ``_encode_chunk`` /
   SRHT rotation). IVF-RaBitQ's observation that coarse k-means is
   essentially the whole build cost is what makes this affordable: the
   incremental cycle touches a few lists' rows, never the corpus.
   When the raw vectors are gone (pq/bq payloads), rows come from the
   codes' own reconstruction (``reconstruct_rows``) unless the caller
   provides an exact ``row_source``.
3. **swap** (``serving.maintenance.swap``) — adopt the staged clone via
   :meth:`~raft_tpu.serving.PagedListStore.recluster_swap`: the same
   mutation-version optimistic-concurrency as compaction (racing
   mutations abort classified-``stale``; in-flight searches keep their
   snapshots), and because the centers array keeps its shape and the
   clone keeps the pool capacity/table width, every compiled scan program
   re-dispatches — maintenance never recompiles the data plane.

``CompactionManager`` rides along as the tombstone policy: ``pump()``
drives it first, then measures drift, then re-clusters when the skew or
recall component is what crossed the line (tombstone-dominant drift IS
compaction's job). Failures classify into counters + the event ring; an
admission check (obs/costmodel) prices the staging clone — which
transiently doubles the store's resident footprint — before any work.

Drive it deterministically (:meth:`MaintenanceManager.pump` in serving
idle gaps — what the bench and tier-1 do) or with the background worker
(:meth:`start` / :meth:`stop`).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from raft_tpu import obs, resilience
from raft_tpu.resilience.retry import record_event
from raft_tpu.serving.compaction import CompactionManager, _env_float
from raft_tpu.serving.store import PagedListStore, _pow2_at_least

MAINT_DRIFT_ENV = "RAFT_TPU_MAINT_DRIFT_THRESHOLD"
MAINT_SKEW_ENV = "RAFT_TPU_MAINT_SPLIT_SKEW"
MAINT_DEADLINE_ENV = "RAFT_TPU_MAINT_DEADLINE_S"
MAINT_INTERVAL_ENV = "RAFT_TPU_MAINT_INTERVAL_S"
MAINT_PAIRS_ENV = "RAFT_TPU_MAINT_MAX_PAIRS"

_DEFAULT_DRIFT = 1.0
_DEFAULT_SKEW = 4.0
_DEFAULT_DEADLINE_S = 30.0
_DEFAULT_INTERVAL_S = 0.5
_DEFAULT_PAIRS = 4
# the tombstone component's normalizer when running without a compaction
# policy: the same default trigger a CompactionManager would have used
_DEFAULT_RATIO_FALLBACK = 0.25


def default_drift_threshold() -> float:
    """Drift score at which a cycle is warranted
    (``RAFT_TPU_MAINT_DRIFT_THRESHOLD``, default 1.0 — the score is
    pre-normalized so 1.0 means "a signal crossed its own trigger")."""
    return _env_float(MAINT_DRIFT_ENV, _DEFAULT_DRIFT)


def default_split_skew() -> float:
    """Per-list fill multiple of the mean above which a list is split
    (``RAFT_TPU_MAINT_SPLIT_SKEW``, default 4.0 — the packed layout's
    auto-list-cap allowance, so a split fires about when the packed
    build would have spilled)."""
    return _env_float(MAINT_SKEW_ENV, _DEFAULT_SKEW)


def default_maintenance_deadline() -> float:
    """Per-phase wall-clock bound in seconds
    (``RAFT_TPU_MAINT_DEADLINE_S``, default 30)."""
    return _env_float(MAINT_DEADLINE_ENV, _DEFAULT_DEADLINE_S)


def default_maintenance_interval() -> float:
    """Background worker poll interval in seconds
    (``RAFT_TPU_MAINT_INTERVAL_S``, default 0.5)."""
    return _env_float(MAINT_INTERVAL_ENV, _DEFAULT_INTERVAL_S)


def default_max_pairs() -> int:
    """Hot/cold list pairs re-clustered per cycle
    (``RAFT_TPU_MAINT_MAX_PAIRS``, default 4 — incremental by design:
    many small cycles beat one rebuild-sized one)."""
    return max(1, int(_env_float(MAINT_PAIRS_ENV, _DEFAULT_PAIRS)))


class MaintenanceManager:
    """Drift-triggered background maintenance driver for one paged store.

    ``sampler`` (optional :class:`~raft_tpu.obs.shadow.ShadowSampler`)
    supplies the recall trend; ``compaction`` the tombstone policy (a
    default :class:`CompactionManager` is built when omitted; pass None
    explicitly to run without one). ``row_source(ids) -> (n, dim)
    float32`` overrides the code-reconstruction row source for pq/bq
    stores when the caller kept the raw vectors.

    Thread-safe like the compaction manager: counters live under their
    own leaf ``_stats_lock`` (never held across store calls), one cycle
    at a time serializes on ``_busy``.
    """

    def __init__(self, store: PagedListStore, *, sampler=None,
                 compaction="auto",
                 row_source: Optional[Callable] = None,
                 drift_threshold: Optional[float] = None,
                 split_skew: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 max_pairs: Optional[int] = None,
                 min_split_rows: int = 8):
        if not isinstance(store, PagedListStore):
            raise TypeError(
                "MaintenanceManager maintains a PagedListStore; got "
                f"{type(store).__name__} (packed indexes are immutable — "
                "wrap with PagedListStore.from_index first)")
        self.store = store
        self.sampler = sampler
        self.compaction = (CompactionManager(store)
                           if compaction == "auto" else compaction)
        self.row_source = row_source
        self.drift_threshold = float(
            drift_threshold if drift_threshold is not None
            else default_drift_threshold())
        self.split_skew = max(1.001, float(
            split_skew if split_skew is not None else default_split_skew()))
        self.deadline_s = float(deadline_s if deadline_s is not None
                                else default_maintenance_deadline())
        self.interval_s = float(interval_s if interval_s is not None
                                else default_maintenance_interval())
        self.max_pairs = int(max_pairs if max_pairs is not None
                             else default_max_pairs())
        self.min_split_rows = max(4, int(min_split_rows))
        # counter plane: mutated by whichever thread wins _busy, read by
        # stats()/report() from serving threads — its own leaf lock,
        # never held across store or sampler calls
        self._stats_lock = threading.Lock()
        self.cycles = 0         # guarded-by: _stats_lock, reads-ok
        self.stale_aborts = 0   # guarded-by: _stats_lock, reads-ok
        self.failures = 0       # guarded-by: _stats_lock, reads-ok
        self.skipped = 0        # guarded-by: _stats_lock, reads-ok -- denied/noop-degenerate cycles
        self.drift_events = 0   # guarded-by: _stats_lock, reads-ok
        self.pairs_total = 0    # guarded-by: _stats_lock, reads-ok
        self.rows_moved = 0     # guarded-by: _stats_lock, reads-ok
        self.drift_score = 0.0  # guarded-by: _stats_lock, reads-ok
        self.list_skew = 0.0    # guarded-by: _stats_lock, reads-ok
        self.last_status: Optional[str] = None  # guarded-by: _stats_lock, reads-ok
        self.last_duration_s: Optional[float] = None  # guarded-by: _stats_lock, reads-ok
        # first healthy shadow estimate: the (recall, ci_low) band every
        # later estimate is judged against
        self._recall_base: Optional[tuple] = None  # guarded-by: _stats_lock, reads-ok
        self._recall_last: Optional[float] = None  # guarded-by: _stats_lock, reads-ok
        self._busy = threading.Lock()
        self._worker: Optional[threading.Thread] = None
        self._stopping = False

    # -- drift detection ----------------------------------------------------
    def _recall_component(self) -> tuple:
        """``(excess, estimate)`` — recall decay measured in units of the
        BASELINE Wilson half-width: >= 1.0 means the live estimate fell
        out of the CI band the first healthy window established. 0.0
        while the sampler is absent, stale, or still establishing."""
        if self.sampler is None:
            return 0.0, None
        est = self.sampler.estimate()
        if est["recall"] is None or est["stale"]:
            return 0.0, est
        with self._stats_lock:
            if self._recall_base is None and est["samples"] >= 8:
                self._recall_base = (est["recall"], est["ci_low"])
            base = self._recall_base
            self._recall_last = est["recall"]
        if base is None:
            return 0.0, est
        half = max(base[0] - base[1], 1e-6)
        return max(0.0, (base[0] - est["recall"]) / half), est

    def detect(self) -> dict:
        """One drift measurement: skew, tombstone and recall components
        (each normalized by its own trigger), folded as their max into
        ``drift_score`` and exported as gauges. Crossing
        ``drift_threshold`` files a classified ``drift_detected`` event
        naming the dominant signal. Deadline-bounded and faultpointed
        (``serving.maintenance.detect``) like every maintenance phase."""
        with obs.record_span("serving::maintenance_detect"):
            with resilience.Deadline(self.deadline_s,
                                     label="serving.maintenance.detect"):
                resilience.faultpoint("serving.maintenance.detect")
                skew = self.store.list_skew()
                tomb = float(self.store.tombstone_ratio)
                recall_x, est = self._recall_component()
        comp_ratio = (self.compaction.ratio if self.compaction is not None
                      else _DEFAULT_RATIO_FALLBACK)
        components = {
            "skew": skew / self.split_skew,
            "tombstones": tomb / max(comp_ratio, 1e-9),
            "recall": recall_x,
        }
        score = max(components.values())
        dominant = max(components, key=components.get)
        drifted = score >= self.drift_threshold
        with self._stats_lock:
            self.drift_score = score
            self.list_skew = skew
            if drifted:
                self.drift_events += 1
        if obs.enabled():
            obs.set_gauge("store.list_skew", skew)
            obs.set_gauge("store.drift_score", score)
        if drifted:
            obs.add("serving.maintenance.drift_detected")
            record_event("drift_detected", signal=dominant,
                         drift_score=round(score, 4),
                         list_skew=round(skew, 4),
                         tombstone_ratio=round(tomb, 4),
                         recall_component=round(recall_x, 4))
        return {"drift_score": score, "list_skew": skew,
                "tombstone_ratio": tomb, "components": components,
                "dominant": dominant, "drifted": drifted,
                "recall_estimate": None if est is None else est["recall"]}

    # -- re-clustering ------------------------------------------------------
    def _plan_pairs(self, counts: np.ndarray) -> list:
        """(hot, cold) list pairs for this cycle: the hottest lists above
        ``split_skew``× the mean fill, paired hottest-first with the
        emptiest donors below the mean. Hot and cold sets are disjoint by
        construction (split_skew > 1), capped at ``max_pairs``."""
        total = int(counts.sum())
        n = counts.shape[0]
        if total == 0 or n < 2:
            return []
        mean = total / n
        order = np.argsort(counts, kind="stable")
        hots = [int(l) for l in order[::-1]
                if counts[l] > self.split_skew * mean
                and counts[l] >= self.min_split_rows]
        colds = [int(l) for l in order if counts[l] < mean]
        return list(zip(hots, colds))[:self.max_pairs]

    def _rows_for(self, payload, extra, ids_np, labels_np, idx) -> jnp.ndarray:
        """Assignment-grade float32 vectors for the selected live rows:
        the raw payload for flat stores, the caller's ``row_source`` when
        provided, else the codes' own reconstruction (exact codeword /
        RaBitQ projection, un-rotated — neighbors ``reconstruct_rows``).
        Reconstruction uses the CURRENT centers and OLD labels: the codes
        were encoded against them."""
        store = self.store
        if self.row_source is not None:
            rows = jnp.asarray(
                np.asarray(self.row_source(np.asarray(ids_np)[idx]),
                           np.float32))
        elif store.kind == "ivf_flat":
            rows = jnp.take(payload, jnp.asarray(idx),
                            axis=0).astype(jnp.float32)
        elif store.kind == "ivf_pq":
            from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

            rows = ivf_pq_mod.reconstruct_rows(
                store.centers, store.rotation, store.codebooks,
                jnp.take(payload, jnp.asarray(idx), axis=0),
                jnp.asarray(labels_np[idx]), store.pq_dim, store.pq_bits,
                store.dim)
        else:
            from raft_tpu.neighbors import ivf_bq as ivf_bq_mod

            rows = ivf_bq_mod.reconstruct_rows(
                store.centers, store.rotation,
                jnp.take(payload, jnp.asarray(idx), axis=0),
                jnp.take(extra, jnp.asarray(idx), axis=0),
                jnp.asarray(labels_np[idx]), store.bq_bits,
                store.rotation_kind, store.dim)
        if store.metric == "cosine":
            rows = rows / jnp.maximum(
                jnp.linalg.norm(rows, axis=1, keepdims=True), 1e-30)
        return rows

    def _admission_denied(self, pairs: int) -> bool:
        """Price the staging clone (it transiently doubles the store's
        resident pools) through the costmodel admission gate; REJECT skips
        the cycle classified-``denied``. The check itself never raises
        (check_admission's contract) — a broken layout probe degrades to
        an admit, classified there."""
        from raft_tpu.obs import costmodel

        layout = costmodel.index_layout(self.store)
        predicted = costmodel.predict_index_bytes(**layout)
        verdict = costmodel.check_admission(
            predicted, entry="serving.maintenance.recluster")
        if verdict.get("verdict") != costmodel.REJECT:
            return False
        obs.add("serving.maintenance.denied")
        record_event("maintenance_denied", pairs=pairs,
                     predicted_bytes=int(predicted))
        return True

    def _stage_clone(self, pairs: list):
        """Build the staging clone for this cycle's split/merge plan:
        relabel, re-encode ONLY the affected rows, ingest every surviving
        row in snapshot order. Returns ``(clone, n_pairs, n_moved)`` or
        None when the plan degenerates (nothing split)."""
        store = self.store
        payload, aux, extra, ids_np, labels_np = store._live_rows()
        n = int(ids_np.shape[0])
        if n == 0:
            return None
        labels_new = labels_np.astype(np.int32).copy()
        centers_new = np.array(store.centers, np.float32, copy=True)
        split_lists: list = []
        for h, c in pairs:
            h_idx = np.nonzero(labels_np == h)[0]
            if h_idx.size < self.min_split_rows:
                continue
            from raft_tpu.neighbors import ivf_flat as ivf_flat_mod

            rows_h = np.asarray(
                self._rows_for(payload, extra, ids_np, labels_np, h_idx))
            c2, assign = ivf_flat_mod.split_list_rows(rows_h)
            if assign.min() == assign.max():
                continue  # degenerate (identical rows): leave the list be
            centers_new[h] = c2[0]
            centers_new[c] = c2[1]
            labels_new[h_idx] = np.where(assign == 0, h, c).astype(np.int32)
            split_lists.append((h, c))
        if not split_lists:
            return None
        # donor rows: their center was replaced by the split's second
        # half — re-home each to its nearest NEW center (full centers
        # array, one small host matmul per cycle)
        donor_idx = np.nonzero(np.isin(
            labels_np, [c for _, c in split_lists]))[0]
        if donor_idx.size:
            rows_d = np.asarray(self._rows_for(
                payload, extra, ids_np, labels_np, donor_idx))
            if store.metric in ("cosine", "inner_product"):
                labels_new[donor_idx] = np.argmax(
                    rows_d @ centers_new.T, axis=1).astype(np.int32)
            else:
                d2 = ((rows_d ** 2).sum(1, keepdims=True)
                      - 2.0 * rows_d @ centers_new.T
                      + (centers_new ** 2).sum(1)[None, :])
                labels_new[donor_idx] = np.argmin(d2, axis=1).astype(np.int32)
        moved = np.nonzero(labels_new != labels_np)[0]
        # every row whose NEW home is a split slot sits on a moved center
        # even if its label survived — pq/bq encodings reference the
        # center, so those rows re-encode too
        touched_lists = np.array(sorted(
            {l for hc in split_lists for l in hc}), np.int32)
        affected = np.union1d(moved, np.nonzero(
            np.isin(labels_new, touched_lists))[0])
        clone = store._empty_clone(centers=jnp.asarray(centers_new))
        if store.kind == "ivf_flat" or affected.size == 0:
            payload_new, aux_new, extra_new = payload, aux, extra
        else:
            # pow2-bucketed re-encode (repeat-pad, slice back) so a
            # lifetime of arbitrary affected-set sizes compiles
            # O(log max) encode programs, the _append scatter discipline
            n_aff = int(affected.size)
            bucket = _pow2_at_least(n_aff)
            sel = np.concatenate(
                [affected, np.repeat(affected[:1], bucket - n_aff)])
            work = self._rows_for(payload, extra, ids_np, labels_np, sel)
            p_b, a_b, _, e_b = clone._prepare_payload(work, labels_new[sel])
            idx_dev = jnp.asarray(affected)
            payload_new = payload.at[idx_dev].set(p_b[:n_aff])
            aux_new = aux.at[idx_dev].set(a_b[:n_aff])
            extra_new = (None if extra is None
                         else extra.at[idx_dev].set(e_b[:n_aff]))
        labels_dev = jnp.asarray(labels_new)
        if store.kind == "ivf_pq":
            from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

            # the decoded int8 cache is a deterministic function of the
            # codes (bitwise-stable across recomputes), and _live_rows
            # does not carry it — rebuild it whole for the clone
            extra_new = ivf_pq_mod._decode_code_rows(
                store.codebooks, payload_new, store.decoded_scale,
                store.pq_dim, store.pq_bits)
            if store.metric in ("sqeuclidean", "euclidean"):
                rc2 = ivf_pq_mod._center_rot_sqnorm(clone.centers,
                                                    store.rotation)
                bias_new = rc2[labels_dev] + aux_new
            else:
                bias_new = aux_new
        else:
            # flat: norms/zeros; bq: aux IS the scan bias at live rows
            bias_new = aux_new
        with clone._lock:
            clone._ingest_rows(payload_new, ids_np, aux_new, labels_new,
                               bias_new, extra_new)
        if obs.enabled():
            from raft_tpu.obs import roofline as obs_roofline

            rot_dim = (0 if store.rotation is None
                       else int(store.rotation.shape[-1]))
            obs_roofline.note_dispatch(
                "serving.maintenance.reencode",
                {"n_rows": int(affected.size), "dim": store.dim,
                 "rot_dim": 0 if store.kind == "ivf_flat" else rot_dim,
                 "pq_dim": store.pq_dim if store.kind == "ivf_pq" else 0,
                 "n_codes": (int(store.codebooks.shape[1])
                             if store.kind == "ivf_pq" else 0)})
        return clone, len(split_lists), int(moved.size)

    def recluster(self) -> dict:
        """One incremental re-clustering cycle: plan hot/cold pairs from
        the live fill counts, stage a same-shape clone off the hot path
        (``serving.maintenance.recluster``), swap it in atomically
        (``serving.maintenance.swap``). Every outcome is classified:
        ``ok`` / ``noop`` / ``denied`` / ``stale`` / an exception kind."""
        store = self.store
        t0 = time.perf_counter()
        v0 = store.mutation_version
        try:
            with obs.record_span("serving::maintenance_recluster"):
                with resilience.Deadline(
                        self.deadline_s,
                        label="serving.maintenance.recluster"):
                    # faultpoint INSIDE the deadline scope: an armed hang
                    # spins on check_interrupt bounded by deadline_s
                    resilience.faultpoint("serving.maintenance.recluster")
                    pairs = self._plan_pairs(store.list_fill_counts())
                    if not pairs:
                        staged = None
                    elif self._admission_denied(len(pairs)):
                        return self._finish("denied", t0, 0, 0)
                    else:
                        staged = self._stage_clone(pairs)
            if staged is None:
                return self._finish("noop", t0, 0, 0)
            clone, n_pairs, n_moved = staged
            with obs.record_span("serving::maintenance_swap"):
                with resilience.Deadline(self.deadline_s,
                                         label="serving.maintenance.swap"):
                    resilience.faultpoint("serving.maintenance.swap")
                    swapped = store.recluster_swap(clone, v0)
        except Exception as e:
            kind = resilience.classify(e)
            with self._stats_lock:
                self.failures += 1
                self.last_status = kind
                self.last_duration_s = time.perf_counter() - t0
            obs.add(f"serving.maintenance.{kind.lower()}")
            record_event("maintenance_error", kind=kind, version=v0,
                         error=repr(e)[:200])
            return {"status": kind, "duration_s": self.last_duration_s}
        if not swapped:
            # a mutation landed between the snapshot and the swap: the
            # staged work is discarded, nothing changed, the next pump
            # retries against the new version — classified, never silent
            out = self._finish("stale", t0, n_pairs, 0)
            record_event("maintenance_stale", version=v0, pairs=n_pairs)
            return out
        out = self._finish("ok", t0, n_pairs, n_moved)
        record_event("maintenance_recluster", pairs=n_pairs,
                     rows_moved=n_moved, version=v0,
                     skew_after=round(store.list_skew(), 4))
        return out

    def _finish(self, status: str, t0: float, n_pairs: int,
                n_moved: int) -> dict:
        dt = time.perf_counter() - t0
        with self._stats_lock:
            self.last_status = status
            self.last_duration_s = dt
            if status == "ok":
                self.cycles += 1
                self.pairs_total += n_pairs
                self.rows_moved += n_moved
            elif status == "stale":
                self.stale_aborts += 1
            else:
                self.skipped += 1
        obs.add(f"serving.maintenance.{status}")
        if status == "ok" and obs.enabled():
            obs.observe("serving.maintenance.duration_s", dt)
        return {"status": status, "pairs": n_pairs, "rows_moved": n_moved,
                "duration_s": dt}

    # -- scheduling ---------------------------------------------------------
    def pump(self) -> Optional[dict]:
        """One scheduler step: compaction policy first (its own ratio
        trigger), then a drift measurement, then — when the skew or
        recall component is what crossed the threshold — one
        re-clustering cycle. Returns the step's record, or None when a
        concurrent pump held ``_busy``. The deterministic driver for
        serving loops and tier-1."""
        if not self._busy.acquire(blocking=False):
            return None
        try:
            compact_out = (self.compaction.pump()
                           if self.compaction is not None else None)
            try:
                sig = self.detect()
            except Exception as e:
                kind = resilience.classify(e)
                with self._stats_lock:
                    self.failures += 1
                    self.last_status = kind
                obs.add(f"serving.maintenance.{kind.lower()}")
                record_event("maintenance_error", kind=kind, phase="detect",
                             error=repr(e)[:200])
                return {"status": kind, "phase": "detect",
                        "compaction": compact_out}
            recluster_out = None
            if sig["drifted"] and sig["dominant"] != "tombstones":
                recluster_out = self.recluster()
            return {"status": (recluster_out or {}).get("status", "idle"),
                    "drift": sig, "recluster": recluster_out,
                    "compaction": compact_out}
        finally:
            self._busy.release()

    # -- worker -------------------------------------------------------------
    def start(self) -> None:
        """Run the maintenance loop on a daemon worker thread — drift
        response truly off the serving thread (pump-in-idle-gaps stays
        available for deterministic runs)."""
        if self._worker is not None and self._worker.is_alive():
            return
        self._stopping = False
        self._worker = threading.Thread(
            target=self._run_loop, name="raft-tpu-maintenance", daemon=True)
        self._worker.start()

    def _run_loop(self) -> None:
        while not self._stopping:
            self.pump()
            time.sleep(self.interval_s)

    def stop(self, timeout: float = 30.0) -> None:
        self._stopping = True
        if self._worker is not None:
            self._worker.join(timeout=timeout)
            self._worker = None

    # -- reporting ----------------------------------------------------------
    def report(self) -> dict:
        """The obs report's ``maintenance`` section (and ``stats()``
        alias): drift state, cycle counters, and the recall trend the
        drift detector is holding the store to."""
        comp = (self.compaction.stats()
                if self.compaction is not None else None)
        skew_now = self.store.list_skew()  # store call OUTSIDE the lock
        with self._stats_lock:
            base = self._recall_base
            recall = {
                "baseline": None if base is None else round(base[0], 4),
                "baseline_ci_low": None if base is None else round(base[1], 4),
                "estimate": (None if self._recall_last is None
                             else round(self._recall_last, 4)),
                "decay": (None if base is None or self._recall_last is None
                          else round(base[0] - self._recall_last, 4)),
            }
            return {
                "drift_score": round(self.drift_score, 4),
                "list_skew": round(skew_now, 4),
                "cycles": self.cycles,
                "stale_aborts": self.stale_aborts,
                "failures": self.failures,
                "skipped": self.skipped,
                "drift_events": self.drift_events,
                "pairs_total": self.pairs_total,
                "rows_moved": self.rows_moved,
                "last_status": self.last_status,
                "last_duration_s": self.last_duration_s,
                "recall": recall,
                "drift_threshold": self.drift_threshold,
                "split_skew": self.split_skew,
                "compaction": comp,
            }

    stats = report

