"""SLO-aware dynamic query batching: one-at-a-time in, device batches out.

Production queries arrive one at a time; TPU throughput comes from
batches. The :class:`QueryQueue` bridges the two: single requests with
per-request deadlines (riding :class:`raft_tpu.resilience.Deadline`) are
coalesced into device batches whose size is chosen dynamically under a
latency SLO, dispatched through the existing search entry points, and the
batched results demultiplexed back per request.

Admission policy — **admit-until-deadline-pressure**: a forming batch
keeps admitting queued requests while the tightest pending deadline still
leaves room for one more dispatch (estimated from a per-bucket EWMA of
measured batch latency). It dispatches as soon as (a) the pool hits the
current batch cap, (b) the tightest deadline's slack falls below the
estimated dispatch latency plus margin, or (c) the oldest request has
waited ``fill_wait_s`` — so light traffic pays at most ``fill_wait_s``
extra latency and heavy traffic gets full batches.

Batch shapes are drawn from a small power-of-two **bucket ladder**
(1, 2, 4, …, ``max_batch``) so a lifetime of arbitrary traffic compiles
O(log max_batch) search programs — the Memory Safe Computations concern
(PAPERS.md): batch-size changes must not blow HBM or recompile.

Failure semantics (standing gates): the dispatch carries the
``serving.queue.dispatch`` faultpoint; an expired request is drained with
a **classified DEADLINE verdict** (never a fleet failure), an
OOM-classified dispatch **halves the batch cap** and requeues (adaptive
degradation, ``degrade_on_oom`` style), a TRANSIENT dispatch retries
once, and a FATAL error is delivered — classified — to exactly the
requests in that batch while the queue keeps serving. Requeued-once
survivors are counted (``serving.queue.requeued``) and flagged on their
dispatch span, so SLO burn-rate math over the once-per-request verdict
counters never double-counts their first admission.

**Pre-dispatch admission** (round 11, BINDING since round 18): with a
``cost_model`` hook (``obs.costmodel.paged_scan_estimator(store, k,
n_probes)``), every batch dispatch first runs
``costmodel.check_admission`` — its predicted HBM footprint projected
against the live watermark and budget — and the classified
ADMIT/QUEUE/REJECT verdict lands as gauges, events and a dispatch-span
attribute. With a ``capacity=`` controller
(:class:`raft_tpu.serving.CapacityController`) the verdict is POLICY:
ADMIT dispatches; QUEUE holds the batch (requeued at the front, a short
hold backoff, re-checked next pump — requests past their deadline drain
with the classified DEADLINE verdict, so a sustained squeeze can never
hang the queue); REJECT (after the controller's own eviction attempt)
delivers the classified ``rejected`` verdict to exactly that batch while
the queue keeps serving. Without ``capacity`` the hook stays
record-only. Each dispatch also runs under ``obs.compile.watch()``, so a
mid-traffic retrace is stamped with the wall-clock it cost in the
compile ledger.

**Per-request traces** (round 10): with telemetry on, every request gets
its own trace — ``submit → admit → dispatch → complete`` recorded as
children of one ``serving::request`` root via the explicit-lineage path
(``obs.tracing.manual_span``; the lifecycle crosses the caller thread and
the batcher, so contextvar parenting cannot link it), carrying
``queue_wait_s`` / ``batch_size`` / ``bucket`` / ``requeued`` attrs. The
request-latency histogram links its exemplar ring to these trace ids, so
"what did the p99 bucket look like?" dereferences to concrete requests.
With telemetry OFF the hot path is unchanged: the same single
``obs.enabled()`` branch, no per-request allocation, no trace, no host
sync (tier-1 asserts the handle's ``trace_id`` stays None and the span
ring stays empty).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from raft_tpu import obs, resilience
from raft_tpu.obs import compile as obs_compile
from raft_tpu.resilience.deadline import DeadlineExceeded
from raft_tpu.resilience.retry import record_event

_OK = "ok"


class _Request:
    __slots__ = ("query", "t_arrive", "t_deadline", "event", "vals", "ids",
                 "verdict", "error", "retries", "requeued", "_latency_s",
                 "trace_id", "span_id", "t_epoch", "t_admit")

    def __init__(self, query: np.ndarray, t_arrive: float, t_deadline: float):
        self.query = query
        self.t_arrive = t_arrive
        self.t_deadline = t_deadline
        self.event = threading.Event()
        self.vals = None
        self.ids = None
        self.verdict: Optional[str] = None  # "ok" | resilience kind
        self.error: Optional[BaseException] = None
        self.retries = 0
        self.requeued = False
        # trace identity: allocated at submit ONLY under obs.enabled() —
        # the telemetry-off hot path must not pay id allocation
        self.trace_id: Optional[str] = None
        self.span_id: Optional[str] = None
        self.t_epoch = 0.0   # epoch twin of t_arrive (span t0 convention)
        self.t_admit = 0.0   # monotonic admit time (queue_wait_s source)


class RequestHandle:
    """Caller-side view of one submitted query."""

    def __init__(self, req: _Request):
        self._req = req

    def done(self) -> bool:
        return self._req.event.is_set()

    @property
    def verdict(self) -> Optional[str]:
        """``"ok"``, a :mod:`raft_tpu.resilience` failure kind, or None
        while pending."""
        return self._req.verdict

    @property
    def trace_id(self) -> Optional[str]:
        """This request's trace id (the ``serving::request`` span tree in
        ``obs.tracing``); None when telemetry was off at submit."""
        return self._req.trace_id

    @property
    def latency_s(self) -> Optional[float]:
        return getattr(self._req, "_latency_s", None)

    def result(self, timeout: Optional[float] = None):
        """Block for the per-request ``(distances, indices)`` rows.
        Raises :class:`~raft_tpu.resilience.DeadlineExceeded` on a
        DEADLINE verdict and the classified original error otherwise."""
        if not self._req.event.wait(timeout):
            raise TimeoutError("request still pending")
        if self._req.verdict == _OK:
            return self._req.vals, self._req.ids
        if self._req.verdict == resilience.DEADLINE:
            raise self._req.error or DeadlineExceeded(
                "DEADLINE_EXCEEDED: request expired in queue")
        raise self._req.error


def _buckets(max_batch: int) -> List[int]:
    out = [1]
    while out[-1] < max_batch:
        out.append(min(out[-1] * 2, max_batch))
    return out


class QueryQueue:
    """Host-side request queue + dynamic batcher over one search callable.

    ``search_fn(queries_2d) -> (distances, indices)`` is any existing
    search entry point closed over its index/store and parameters —
    :func:`raft_tpu.serving.searcher` builds the paged-store one.

    Drive it either with the background worker (:meth:`start` /
    :meth:`stop`) or synchronously (:meth:`pump` in a caller loop — what
    the bench's arrival simulator and the deterministic tier-1 tests do).
    """

    def __init__(self, search_fn: Callable, *,
                 slo_s: float = 0.05,
                 max_batch: int = 64,
                 fill_wait_s: Optional[float] = None,
                 default_timeout_s: Optional[float] = None,
                 pressure_margin_s: float = 0.002,
                 shadow=None,
                 cost_model: Optional[Callable] = None,
                 capacity=None, tenant: str = ""):
        self._search_fn = search_fn
        # optional online-recall shadow sampler (obs/shadow.ShadowSampler):
        # served results are OFFERED after each successful dispatch — one
        # seeded-hash decision per request, drop-on-pressure, never blocking
        self._shadow = shadow
        # optional pre-dispatch cost hook (round 11): ``batch_size -> bytes
        # or obs.costmodel.estimate dict``; each dispatch is first run
        # through ``costmodel.check_admission`` and the ADMIT/QUEUE/REJECT
        # verdict lands as gauges + classified events and on the dispatch
        # span. Observability only — a non-admit verdict does NOT block the
        # dispatch here; acting on it is the ROADMAP item-4 admission
        # controller, which consumes exactly these records.
        # (``costmodel.paged_scan_estimator(store, k, n_probes)`` builds
        # the hook for a paged store.)
        self._cost_model = cost_model
        # round 18: with a CapacityController the verdict ACTS (see the
        # module docstring) — QUEUE holds the batch, REJECT delivers the
        # classified ``rejected`` verdict after the controller's eviction
        # attempt. ``_hold_until`` is the QUEUE-hold backoff: the pump
        # loop stops re-popping a held batch every iteration while
        # deadline expiry keeps draining underneath it.
        self._capacity = capacity
        # the tenant this queue serves (optional): the controller's
        # eviction never demotes the tenant whose dispatch it is sizing,
        # and the verdict lands in that tenant's per-tenant counts
        self._tenant = str(tenant)
        self._hold_until = 0.0  # guarded-by: _cv
        self.slo_s = float(slo_s)
        self.max_batch = int(max_batch)
        self.buckets = _buckets(self.max_batch)
        self.fill_wait_s = (float(fill_wait_s) if fill_wait_s is not None
                            else self.slo_s / 2.0)
        self.default_timeout_s = default_timeout_s
        self.pressure_margin_s = float(pressure_margin_s)
        self._pending: deque = deque()  # guarded-by: _cv
        self._cv = threading.Condition()
        self._lat_ewma: Dict[int, float] = {}  # guarded-by: _cv -- bucket -> s
        self._batch_cap = self.max_batch  # guarded-by: _cv, reads-ok -- halved on OOM
        self._worker: Optional[threading.Thread] = None
        self._stopping = False  # guarded-by: _cv, reads-ok
        self.batches = 0        # guarded-by: _cv, reads-ok
        self.multi_batches = 0  # guarded-by: _cv, reads-ok

    # -- intake -------------------------------------------------------------
    def submit(self, query, timeout_s: Optional[float] = None) -> RequestHandle:
        """Enqueue one query; returns immediately with a handle. The
        request's deadline is ``now + timeout_s`` (or the queue default;
        no deadline when both are None)."""
        q = np.asarray(query, np.float32).reshape(-1)
        now = time.monotonic()
        t = timeout_s if timeout_s is not None else self.default_timeout_s
        req = _Request(q, now, now + t if t is not None else math.inf)
        enabled = obs.enabled()
        if enabled:
            # request trace root ids, allocated BEFORE the request is
            # published: the background worker may dequeue, dispatch and
            # close the request the instant it lands in the deque, and its
            # lifecycle spans must see fully-initialized identity
            tracing = obs.tracing
            req.trace_id = tracing.alloc_id()
            req.span_id = tracing.alloc_id()
            req.t_epoch = time.time()
        with self._cv:
            self._pending.append(req)
            depth = len(self._pending)
            self._cv.notify()
        if enabled:
            # ONE submit record per request (the explicit-lineage child of
            # the request root) + the flat timer series; a second
            # contextvar span here would double every submit in the ring
            dur = time.monotonic() - now
            obs.record_timing("serving::submit", dur)
            tracing.manual_span(
                "serving::submit", t0=req.t_epoch, dur_s=dur,
                trace_id=req.trace_id, parent_id=req.span_id,
                attrs={"depth": depth})
            obs.add("serving.queue.submits")
            obs.observe("serving.queue.depth", depth)
        return RequestHandle(req)

    # -- policy -------------------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _est_latency(self, bucket: int) -> Optional[float]:
        if bucket in self._lat_ewma:
            return self._lat_ewma[bucket]
        known = [v for b, v in self._lat_ewma.items() if b <= bucket]
        return max(known) if known else None

    def _expire_locked(self, now: float) -> List[_Request]:
        """Pop requests that are already past deadline (partial drain)."""
        expired = []
        keep = deque()
        for req in self._pending:
            (expired if req.t_deadline <= now else keep).append(req)
        self._pending = keep
        return expired

    def _ready_locked(self, now: float) -> bool:
        depth = len(self._pending)
        if depth == 0:
            return False
        if now < self._hold_until:
            # capacity QUEUE hold: admission said wait — expired requests
            # still drain (pump expires before it forms batches), so the
            # hold can never become a hang
            return False
        cap = max(1, self._batch_cap)
        if depth >= cap:
            return True
        oldest = min(r.t_arrive for r in self._pending)
        if now - oldest >= self.fill_wait_s:
            return True
        est = self._est_latency(self._bucket_for(min(depth, cap)))
        if est is None:
            # nothing measured yet: assume a dispatch costs a fraction of
            # the SLO (eagerly dispatching instead would burn the warmup
            # window on batch-1 programs)
            est = self.slo_s / 4.0
        tightest = min(r.t_deadline for r in self._pending)
        if tightest - now <= est + self.pressure_margin_s:
            return True  # deadline pressure: admit no further, go now
        return False

    # -- dispatch -----------------------------------------------------------
    def pump(self, now: Optional[float] = None) -> bool:
        """One scheduler step: drain expired requests, and dispatch one
        batch if the admission policy says go. Returns True when it did
        either (the caller loop's idle signal)."""
        now = time.monotonic() if now is None else now
        with self._cv:
            expired = self._expire_locked(now)
            batch: List[_Request] = []
            if self._ready_locked(now):
                cap = max(1, self._batch_cap)
                while self._pending and len(batch) < cap:
                    batch.append(self._pending.popleft())
        if batch and obs.enabled():
            t_admit = time.monotonic()
            for req in batch:
                req.t_admit = t_admit
        for req in expired:
            self._finish_deadline(req, "expired in queue")
        if batch:
            self._dispatch(batch)
        return bool(expired or batch)

    def _close_request_trace(self, req: _Request, verdict: str) -> None:
        """Record the request's ``serving::complete`` child and close its
        ``serving::request`` root span (error-tagged for non-ok verdicts).
        No-op for requests submitted with telemetry off — or finished
        after it was switched off (a cleared ring must stay clean)."""
        if req.trace_id is None or not obs.enabled():
            return
        done_epoch = time.time()
        obs.tracing.manual_span(
            "serving::complete", t0=done_epoch, dur_s=0.0,
            trace_id=req.trace_id, parent_id=req.span_id,
            attrs={"verdict": verdict})
        obs.tracing.manual_span(
            "serving::request", t0=req.t_epoch, dur_s=req._latency_s,
            trace_id=req.trace_id, span_id=req.span_id,
            attrs={"verdict": verdict, "requeued": req.requeued},
            error=None if verdict == _OK else verdict)

    def _finish_deadline(self, req: _Request, why: str) -> None:
        req.verdict = resilience.DEADLINE
        req.error = DeadlineExceeded(f"DEADLINE_EXCEEDED: request {why}")
        req._latency_s = time.monotonic() - req.t_arrive
        obs.add("serving.requests.deadline")
        self._close_request_trace(req, resilience.DEADLINE)
        req.event.set()

    def _finish_error(self, req: _Request, kind: str, err: BaseException) -> None:
        req.verdict = kind
        req.error = err
        req._latency_s = time.monotonic() - req.t_arrive
        obs.add(f"serving.requests.{kind.lower()}")
        self._close_request_trace(req, kind)
        req.event.set()

    def _finish_rejected(self, req: _Request, err: BaseException) -> None:
        """Capacity-rejected: a FIRST-CLASS classified verdict (round 18)
        — the admission controller refused the dispatch after its
        eviction attempt; the device allocator never saw it (this is
        exactly NOT an OOM)."""
        req.verdict = "rejected"
        req.error = err
        req._latency_s = time.monotonic() - req.t_arrive
        obs.add("serving.requests.rejected")
        self._close_request_trace(req, "rejected")
        req.event.set()

    def _requeue_front(self, reqs: List[_Request], count: bool = True) -> None:
        # requeue accounting (round-10 satellite): survivors of a partial
        # deadline drain or an OOM cap-halving go back for a SECOND
        # admission — counted once here and flagged on their dispatch span,
        # so burn-rate math over the once-per-request verdict counters
        # never sees their first admission twice. A capacity QUEUE hold
        # (round 18) passes count=False: a held batch was never
        # dispatched, and re-counting it every ~2ms hold cycle would
        # inflate the once-per-request series by orders of magnitude —
        # holds have their own counter (serving.capacity.held).
        if count:
            for req in reqs:
                req.requeued = True
            if obs.enabled():
                obs.add("serving.queue.requeued", len(reqs))
        with self._cv:
            for req in reversed(reqs):
                self._pending.appendleft(req)
            self._cv.notify()

    def _dispatch(self, batch: List[_Request]) -> None:
        n = len(batch)
        bucket = self._bucket_for(n)
        qarr = np.stack([r.query for r in batch])
        if bucket != n:
            # pad with copies of row 0: a real vector (not zeros) so the
            # padded rows cannot produce NaN/inf surprises in the scan
            qarr = np.concatenate(
                [qarr, np.repeat(qarr[:1], bucket - n, axis=0)])
        now = time.monotonic()
        budget = min(r.t_deadline for r in batch) - now
        verdict_rec = None
        if self._cost_model is not None:
            # pre-dispatch admission (round 11; BINDING with a capacity
            # controller since round 18): predict the batch's footprint,
            # check admission, record the classified verdict — never
            # raises
            from raft_tpu.obs import costmodel

            try:
                predicted = self._cost_model(bucket)
            except Exception as e:
                record_event("serving_cost_model_error",
                             kind=resilience.classify(e),
                             error=repr(e)[:200])
                predicted = None
            if predicted is not None:
                if self._capacity is not None:
                    # the controller's verdict is final AFTER its own
                    # eviction attempt (REJECT → demote LRU tenants →
                    # re-check); it never raises
                    try:
                        verdict_rec = self._capacity.admit(
                            predicted, entry="serving.dispatch",
                            tenant=self._tenant)
                    except Exception as e:
                        record_event("serving_capacity_error",
                                     kind=resilience.classify(e),
                                     error=repr(e)[:200])
                        verdict_rec = None
                else:
                    verdict_rec = costmodel.check_admission(
                        predicted, entry="serving.dispatch")
            if self._capacity is not None and verdict_rec is not None:
                if verdict_rec["verdict"] == costmodel.QUEUE:
                    # hold under the requests' own deadlines: requeue at
                    # the front with a short backoff — the next pumps
                    # re-check admission, and requests past deadline
                    # drain classified (never a hang)
                    if obs.enabled():
                        obs.add("serving.capacity.held")
                    with self._cv:
                        self._hold_until = time.monotonic() + max(
                            self.pressure_margin_s, 1e-3)
                    self._requeue_front(batch, count=False)
                    return
                if verdict_rec["verdict"] == costmodel.REJECT:
                    from raft_tpu.serving.capacity import CapacityRejected

                    if obs.enabled():
                        obs.add("serving.capacity.rejected_batches")
                    err = CapacityRejected(
                        f"batch of {n} rejected by admission: projected "
                        f"{verdict_rec.get('projected_bytes')} of "
                        f"{verdict_rec.get('budget_bytes')} bytes "
                        f"(shortfall "
                        f"{verdict_rec.get('shortfall_bytes')} B after "
                        f"eviction)")
                    for req in batch:
                        self._finish_rejected(req, err)
                    return
        attrs = None
        if obs.enabled():
            attrs = {"batch": n, "bucket": bucket,
                     "cap": self._batch_cap,
                     "requeued": sum(1 for r in batch if r.requeued)}
            if verdict_rec is not None:
                attrs["admission"] = verdict_rec["verdict"]
        try:
            with obs.record_span("serving::dispatch", attrs=attrs):
                resilience.faultpoint("serving.queue.dispatch")
                with resilience.Deadline(max(budget, 0.0),
                                         label="serving.dispatch"):
                    # ledger watch: a mid-traffic retrace inside this
                    # dispatch gets the dispatch's wall-clock stamped on
                    # its ledger record (obs/compile.py)
                    with obs_compile.watch():
                        vals, ids = self._search_fn(qarr)
                    # force completion INSIDE the deadline scope: a result
                    # is only served once it is actually materialized
                    vals = np.asarray(vals)
                    ids = np.asarray(ids)
        except Exception as e:
            self._on_dispatch_error(batch, e, resilience.classify(e))
            return
        dt = time.monotonic() - now
        with self._cv:
            prev = self._lat_ewma.get(bucket)
            self._lat_ewma[bucket] = (dt if prev is None
                                      else 0.7 * prev + 0.3 * dt)
            self.batches += 1
            if n > 1:
                self.multi_batches += 1
        if obs.enabled():
            obs.observe("serving.batch_latency_s", dt)
            obs.observe("serving.batch.size", n)
            obs.add("serving.batches")
            if n > 1:
                obs.add("serving.batches.multi")
        done = time.monotonic()
        dispatch_epoch = time.time() - dt  # epoch twin of `now`
        for i, req in enumerate(batch):
            req.vals = vals[i]
            req.ids = ids[i]
            req.verdict = _OK
            req._latency_s = done - req.t_arrive
            if obs.enabled():
                if req.trace_id is not None:
                    # lifecycle children under the request root: admit
                    # (covers the queue wait) and dispatch (this batch)
                    wait_s = (req.t_admit or now) - req.t_arrive
                    obs.tracing.manual_span(
                        "serving::admit", t0=req.t_epoch, dur_s=wait_s,
                        trace_id=req.trace_id, parent_id=req.span_id,
                        attrs={"queue_wait_s": wait_s,
                               "requeued": req.requeued})
                    obs.tracing.manual_span(
                        "serving::dispatch", t0=dispatch_epoch, dur_s=dt,
                        trace_id=req.trace_id, parent_id=req.span_id,
                        attrs={"batch_size": n, "bucket": bucket,
                               "queue_wait_s": wait_s,
                               "requeued": req.requeued})
                # exemplar-linked: the latency histogram's percentile
                # buckets dereference to these request traces
                obs.observe("serving.request_latency_s", req._latency_s,
                            trace_id=req.trace_id)
                self._close_request_trace(req, _OK)
            req.event.set()
        if obs.enabled():
            obs.add("serving.requests.ok", n)
        shadow = self._shadow
        if shadow is not None:
            # off-hot-path recall estimation: one seeded decision per
            # request; enqueue-or-drop, never blocks the verdict (requests
            # were already completed above)
            for i, req in enumerate(batch):
                shadow.offer(req.query, ids[i], trace_id=req.trace_id)

    def _on_dispatch_error(self, batch: List[_Request], e: Exception,
                           kind: str) -> None:
        obs.add(f"serving.dispatch.{kind.lower()}")
        record_event("serving_dispatch_error", kind=kind, batch=len(batch),
                     error=repr(e)[:200])
        now = time.monotonic()
        if kind == resilience.OOM and self._batch_cap > 1:
            # adaptive degradation: halve the cap and requeue — the next
            # pumps re-dispatch the same requests in smaller batches
            with self._cv:
                cap = self._batch_cap = max(1, self._batch_cap // 2)
            obs.add("serving.dispatch.oom_halved")
            record_event("serving_batch_halved", cap=cap)
            self._requeue_front(batch)
            return
        if kind in (resilience.DEADLINE, resilience.TRANSIENT):
            # partial drain: requests already past deadline get their
            # DEADLINE verdict; survivors retry once, then fail classified
            retry = []
            for req in batch:
                if req.t_deadline <= now or (kind == resilience.DEADLINE
                                             and req.retries >= 1):
                    self._finish_deadline(req, "deadline during dispatch")
                elif req.retries >= 1:
                    self._finish_error(req, kind, e)
                else:
                    req.retries += 1
                    retry.append(req)
            if retry:
                self._requeue_front(retry)
            return
        for req in batch:  # OOM-at-cap-1 and FATAL: deliver classified
            self._finish_error(req, kind, e)

    # -- worker -------------------------------------------------------------
    def start(self) -> None:
        """Run the scheduler on a daemon worker thread."""
        if self._worker is not None and self._worker.is_alive():
            return
        with self._cv:
            self._stopping = False
        self._worker = threading.Thread(
            target=self._serve_loop, name="raft-tpu-serving", daemon=True)
        self._worker.start()

    def _serve_loop(self) -> None:
        while not self._stopping:
            if self.pump():
                continue
            with self._cv:
                if self._stopping:
                    break
                # wake on submit, or poll at a fraction of the fill wait
                self._cv.wait(timeout=max(self.fill_wait_s / 4, 1e-3))

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the worker; by default first drains queued requests."""
        if drain:
            self.drain(timeout=timeout)
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=5.0)
            self._worker = None

    def drain(self, timeout: float = 30.0) -> None:
        """Serve until the queue is empty (worker running or not)."""
        t_end = time.monotonic() + timeout
        while time.monotonic() < t_end:
            with self._cv:
                empty = not self._pending
            if empty:
                return
            if self._worker is None or not self._worker.is_alive():
                self.pump()
            else:
                time.sleep(1e-3)
        raise TimeoutError(f"queue did not drain within {timeout}s")

    @property
    def depth(self) -> int:
        with self._cv:
            return len(self._pending)

    @property
    def batch_cap(self) -> int:
        """Current adaptive batch-size cap (halved by OOM dispatches)."""
        return self._batch_cap

    def set_batch_cap(self, cap: int) -> int:
        """Clamp the live dispatch cap — the burn-rate controller's batch
        actuator (round 21). Never above ``max_batch`` (no new compiled
        bucket can appear mid-serving), never below 1; returns the cap
        actually installed. The next ``pump`` dispatches under it."""
        with self._cv:
            self._batch_cap = max(1, min(int(cap), self.max_batch))
            self._cv.notify_all()
            return self._batch_cap

    def knobs(self) -> dict:
        """The queue's live config-knob vector — the serving slice of the
        flight recorder's fingerprint (obs/flight.py). Includes the
        ADAPTIVE batch cap, so an OOM-halved window lands as a distinct
        fingerprint group on the frontier, not averaged into the sized
        configuration it no longer runs."""
        return {
            "max_batch": self.max_batch,
            "batch_cap": int(self._batch_cap),
            "slo_s": self.slo_s,
            "fill_wait_s": self.fill_wait_s,
        }
