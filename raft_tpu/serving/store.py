"""Paged mutable IVF storage: fixed-size pages, append-only growth.

The build-once packed layout (neighbors/_packing.py) is immutable by
design — ``extend()`` repacks the whole index, and any change to
``max_list_size`` reshapes every scan operand and recompiles every search
program. Production serving needs the opposite: streaming upserts and
deletes against an index that keeps answering queries, with no repacking
and no recompiles on the mutation path.

The storage pattern is the Ragged Paged Attention TPU kernel's
(PAPERS.md): each ragged sequence — here, each IVF list — owns a chain of
**fixed-size pages** referenced through a page table. Growth appends to
the list's tail page (allocating a fresh page from a free list when the
tail fills); deletion tombstones the row in place (``page_ids == -1``);
the scan walks the page table with masked fill-count tails. Because every
device operand — the page pool ``(capacity_pages, page_rows, ·)``, the
page-id/aux pools, and the ``(n_lists, table_width)`` page table — has a
shape that depends only on *capacity*, not on *fill*, steady-state
upserts/deletes/searches re-dispatch the same compiled programs. Only
capacity growth (page pool doubling, table-width doubling — both
geometric, so O(log n) events over a store's lifetime) retraces. The
Memory Safe Computations line (PAPERS.md) is honored the same way: the
paged scan's working set is bounded by the static ``(n_probes ×
table_width × page_rows)`` gather, sized against the Resources workspace
budget exactly like the packed gather scan.

Three page payloads, one mechanism:

* ``kind="ivf_flat"`` — pages hold raw vectors (same dtype as the
  template index's ``list_data``); per-row aux is the cached L2 norm.
* ``kind="ivf_pq"`` — pages hold packed PQ codes encoded with the
  template index's frozen quantizers (centers/rotation/codebooks); per-row
  aux is the list-side LUT half (``b_sum``), bit-identical to the packed
  build's (the same ``_compute_b_sum`` formula, gathered per row). A
  second ``page_cache`` pool carries the int8 decoded-residual rows the
  paged Pallas scan contracts on the MXU (the packed path's
  ``IvfPqIndex.decoded`` cache, paged).
* ``kind="ivf_bq"`` — pages hold packed 1-bit sign codes (rot_dim/8
  bytes/row, ops/bq_scan layout); per-row aux is the estimator's additive
  term and a ``page_scale`` pool carries the RaBitQ unbiasing factor
  ``f = ‖u‖²/‖u‖₁`` — both produced by the SAME ``_encode_chunk`` the
  packed build uses, so paged↔packed parity holds bitwise.

Round 16 (paged Pallas data plane): every store also maintains a
``page_bias`` pool — the per-row additive bias the strip kernels consume
directly (+inf at tombstones and never-filled tail slots, the packed
kernels' padding convention). Appends write it through the same scatter
that lands the payload; ``delete`` re-stamps +inf in the same dispatch
that tombstones ``page_ids`` — so the paged Pallas scans read the pools
IN PLACE with no per-search bias materialization.

``compact()`` folds the live rows back into the packed representation
(an :class:`~raft_tpu.neighbors.ivf_flat.IvfFlatIndex` /
:class:`~raft_tpu.neighbors.ivf_pq.IvfPqIndex`), which serializes through
the crash-safe v2 snapshot container — the paged store itself is a
serving-time structure and never hits disk directly.

Parity contract (tier-1 enforced): on a store holding exactly the packed
index's rows, ``search_paged`` returns bit-identical top-k ids to the
packed gather scan, and any interleaving of upsert/delete/compact matches
a from-scratch packed build over the surviving rows.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs, resilience
from raft_tpu.obs import compile as obs_compile
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.trace import traced
from raft_tpu.neighbors import ivf_bq as ivf_bq_mod
from raft_tpu.neighbors import ivf_flat as ivf_flat_mod
from raft_tpu.neighbors import ivf_pq as ivf_pq_mod
from raft_tpu.neighbors._packing import pack_lists
from raft_tpu.ops import distance as dist_mod
from raft_tpu.ops import linalg

PAGE_ROWS_ENV = "RAFT_TPU_SERVING_PAGE_ROWS"
_DEFAULT_PAGE_ROWS = 128


def default_page_rows() -> int:
    """Page height: env-tunable (``RAFT_TPU_SERVING_PAGE_ROWS``), default
    128 — small enough that a near-empty list wastes one page, large
    enough that the per-page gather rides full VPU lanes."""
    return max(8, int(os.environ.get(PAGE_ROWS_ENV, _DEFAULT_PAGE_ROWS)))


def _pow2_at_least(v: int) -> int:
    return 1 << max(0, int(v - 1).bit_length())


@jax.jit
def _tombstone(page_ids, page_bias, pp, rr):
    """Scatter -1 ids and +inf bias into (pp, rr) slots in ONE dispatch;
    sentinel coords >= capacity drop. The bias stamp is what makes a
    tombstone invisible to the paged Pallas scans (they read the bias pool
    in place instead of masking on ids)."""
    # ledger registration: pow2-bucketed coords compile O(log) programs —
    # each one lands attributed (obs/compile.py; trace time only). The
    # event runs at TRACE time, so a delete-heavy burst of an already-
    # compiled bucket does zero ledger host work (tier-1 pins the count).
    obs_compile.trace_event("serving.tombstone", page_ids=page_ids,
                            page_bias=page_bias, pp=pp, rr=rr)
    return (page_ids.at[pp, rr].set(-1, mode="drop"),
            page_bias.at[pp, rr].set(jnp.inf, mode="drop"))


def _scatter_rows(pages, page_ids, page_aux, page_bias, extra_pool,
                  payload, ids, aux, bias, extra_rows, pp, rr):
    """Append scatter: one dispatch per (bucketed) chunk. Padded entries
    carry ``pp == capacity`` which ``mode="drop"`` discards. jit'd below —
    kept un-donated: on a failed dispatch the caller's arrays must stay
    valid (upsert commits host metadata only after the scatter lands).
    ``extra_pool`` is the kind-specific second pool (PQ decoded cache /
    BQ scale) or None."""
    # ledger registration: a capacity-growth retrace lands attributed to
    # the pool operand that grew (obs/compile.py; trace time only)
    obs_compile.trace_event("serving.scatter", pages=pages,
                            page_ids=page_ids, page_aux=page_aux,
                            page_bias=page_bias, extra_pool=extra_pool,
                            payload=payload, ids=ids, aux=aux, pp=pp, rr=rr)
    pages = pages.at[pp, rr].set(payload, mode="drop")
    page_ids = page_ids.at[pp, rr].set(ids, mode="drop")
    page_aux = page_aux.at[pp, rr].set(aux, mode="drop")
    page_bias = page_bias.at[pp, rr].set(bias, mode="drop")
    if extra_pool is not None:
        extra_pool = extra_pool.at[pp, rr].set(extra_rows, mode="drop")
    return pages, page_ids, page_aux, page_bias, extra_pool


_scatter_rows = jax.jit(_scatter_rows)


@jax.jit
def _flat_row_aux(rows):
    """Per-row L2 norms, the same reduction the packed build applies to
    ``list_data`` (`sqnorm(..., axis=2)` is row-wise too) — parity needs
    the aux bitwise equal, not just close."""
    return dist_mod.sqnorm(rows)


class PagedListStore:
    """Mutable paged IVF storage over a frozen coarse quantizer.

    Created from a built packed index (:meth:`from_index`), which donates
    its centers — and for PQ its rotation/codebooks — as the frozen
    quantizers. Rows then stream in through :meth:`upsert` and out through
    :meth:`delete`; :func:`search_paged` (ivf_flat / ivf_pq) scans the
    pages; :meth:`compact` folds back to the packed layout.

    Thread safety: mutations and the table snapshot take ``_lock``; the
    device scan reads immutable array snapshots, so searches may overlap
    mutations (a search sees the store as of its table snapshot).
    """

    def __init__(self, kind: str, centers, metric: str, *,
                 page_rows: Optional[int] = None,
                 payload_width: int, payload_dtype,
                 rotation=None, codebooks=None, pq_bits: int = 8,
                 pq_dim: int = 0, codebook_kind: str = "subspace",
                 bq_bits: int = 1, rotation_kind: str = "dense",
                 initial_pages: int = 0,
                 res: Optional[Resources] = None):
        if kind not in ("ivf_flat", "ivf_pq", "ivf_bq"):
            raise ValueError(f"unknown store kind {kind!r}")
        if kind == "ivf_pq" and codebook_kind != "subspace":
            raise ValueError(
                "paged ivf_pq serving supports codebook_kind='subspace' "
                "only (the per-cluster LUT scan has no paged path yet)")
        if kind == "ivf_bq" and rotation is None:
            raise ValueError("ivf_bq stores need the index rotation")
        self.kind = kind
        self.metric = metric
        self.centers = jnp.asarray(centers)
        self.rotation = None if rotation is None else jnp.asarray(rotation)
        self.codebooks = None if codebooks is None else jnp.asarray(codebooks)
        self.pq_bits = int(pq_bits)
        self.pq_dim = int(pq_dim)
        self.codebook_kind = codebook_kind
        # BQ extended-code/rotation configuration (round 17): the encode at
        # upsert and the paged scans' plane-extended query operand both key
        # off these (neighbors/ivf_bq docstring)
        self.bq_bits = int(bq_bits)
        self.rotation_kind = rotation_kind
        self.page_rows = int(page_rows or default_page_rows())
        self._res = res or current_resources()
        self._lock = threading.RLock()

        n_lists = int(self.centers.shape[0])
        cap = max(8, _pow2_at_least(initial_pages or n_lists))
        R = self.page_rows
        # Device pools are IMMUTABLE arrays reassigned whole under _lock;
        # off-lock reads (dtype/shape probes, snapshot references) see a
        # consistent old-or-new array — hence reads-ok. The host tables
        # below them are mutated IN PLACE and carry no reads-ok: every
        # read must hold the lock (or come through a locked snapshot).
        self.pages = jnp.zeros((cap, R, payload_width), payload_dtype)  # guarded-by: _lock, reads-ok
        self.page_ids = jnp.full((cap, R), -1, jnp.int32)  # guarded-by: _lock, reads-ok
        # aux init +inf: matches the packed b_sum's +inf-at-padding
        # convention (the flat scan masks on ids, so +inf is inert there)
        self.page_aux = jnp.full((cap, R), jnp.inf, jnp.float32)  # guarded-by: _lock, reads-ok
        # scan-bias pool for the paged Pallas engines: +inf everywhere a
        # row is absent/dead, the per-row additive term where live
        self.page_bias = jnp.full((cap, R), jnp.inf, jnp.float32)  # guarded-by: _lock, reads-ok
        # kind-specific second pool: PQ int8 decoded-residual cache rows
        # (the strip kernel's MXU operand), BQ per-row RaBitQ scale
        self.page_cache = None  # guarded-by: _lock, reads-ok
        self.page_scale = None  # guarded-by: _lock, reads-ok
        if kind == "ivf_pq":
            dsub = int(self.codebooks.shape[2])
            self._cache_dim = self.pq_dim * dsub
            self.page_cache = jnp.zeros((cap, R, self._cache_dim), jnp.int8)
            # the packed path's data-independent dequant scale
            # (ivf_pq._decode_lists: max|codebooks|/127)
            self.decoded_scale = jnp.maximum(
                jnp.max(jnp.abs(self.codebooks)), 1e-30) / 127.0
        elif kind == "ivf_bq":
            self.page_scale = jnp.zeros((cap, R), jnp.float32)

        self._table = np.full((n_lists, 4), -1, np.int32)  # guarded-by: _lock
        self._list_pages = np.zeros(n_lists, np.int32)  # guarded-by: _lock -- chain length
        self._fill = np.zeros(cap, np.int32)  # guarded-by: _lock -- rows ever appended per page
        self._page_list = np.full(cap, -1, np.int32)  # guarded-by: _lock -- owning list, -1 free
        self._free: List[int] = list(range(cap))  # guarded-by: _lock
        self._id_loc: Dict[int, Tuple[int, int]] = {}  # guarded-by: _lock
        self._tombstones = 0  # guarded-by: _lock
        # live (non-tombstoned) rows per list, maintained incrementally by
        # append/tombstone — the drift detector's skew source; an O(n)
        # recount per detect tick would put the hot path's lock under a
        # scan-sized critical section
        self._list_live = np.zeros(n_lists, np.int64)  # guarded-by: _lock
        self._dev_table = None  # guarded-by: _lock -- device mirror, invalidated on table change
        self._dev_lens = None   # guarded-by: _lock -- device chain-length mirror (paged Pallas)
        self._version = 0       # guarded-by: _lock -- bumped on every committed mutation
        self._growths = 0       # guarded-by: _lock
        # standing predicate applied by every search_paged that doesn't
        # pass its own filter; survives compaction/re-clustering swaps
        # (not in _SWAP_FIELDS — clones are built filterless)
        self.filter = None      # guarded-by: _lock, reads-ok

    # -- construction -------------------------------------------------------
    @classmethod
    def from_index(cls, index, *, page_rows: Optional[int] = None,
                   include_rows: bool = True,
                   res: Optional[Resources] = None) -> "PagedListStore":
        """Wrap a built packed index: its quantizers become the store's
        frozen quantizers, and (by default) its live rows are paged in —
        in packed list order, so a freshly wrapped store is scan-parity
        with the index it came from."""
        res = res or current_resources()
        if isinstance(index, ivf_flat_mod.IvfFlatIndex):
            store = cls(
                "ivf_flat", index.centers, index.metric, page_rows=page_rows,
                payload_width=int(index.list_data.shape[2]),
                payload_dtype=index.list_data.dtype, res=res)
        elif isinstance(index, ivf_pq_mod.IvfPqIndex):
            store = cls(
                "ivf_pq", index.centers, index.metric, page_rows=page_rows,
                payload_width=int(index.list_codes.shape[2]),
                payload_dtype=index.list_codes.dtype,
                rotation=index.rotation, codebooks=index.codebooks,
                pq_bits=index.pq_bits, pq_dim=index.pq_dim,
                codebook_kind=index.codebook_kind, res=res)
        elif isinstance(index, ivf_bq_mod.IvfBqIndex):
            store = cls(
                "ivf_bq", index.centers, index.metric, page_rows=page_rows,
                payload_width=int(index.list_codes.shape[2]),
                payload_dtype=index.list_codes.dtype,
                rotation=index.rotation, bq_bits=index.bits,
                rotation_kind=index.rotation_kind, res=res)
        else:
            raise TypeError(f"unsupported index type {type(index).__name__}")
        if include_rows:
            store._ingest_packed(index)
        return store

    def _ingest_packed(self, index) -> None:  # holds: _lock
        """Bulk-append the packed index's live rows, per-list in slot
        order (the arrival order a from-scratch upsert stream would have
        produced). Payloads, aux, scan bias and the kind-specific extra
        pool rows are copied (or derived exactly the way the packed scan
        derives them), not recomputed: the packed build's values ARE the
        parity reference.

        Callers own exclusivity: both call sites (``from_index``,
        ``compact_swap``'s staging clone) ingest into a store no other
        thread can see yet — construction-phase, declared via ``holds``."""
        extra2 = None
        if self.kind == "ivf_flat":
            payload3, ids2 = index.list_data, index.list_ids
            aux2 = index.list_norms
            if aux2 is None:
                aux2 = jnp.zeros_like(ids2, jnp.float32)
            bias2 = aux2  # _ragged_bias: norms (L2) / zeros (ip) at valid
        elif self.kind == "ivf_pq":
            payload3, ids2, aux2 = index.list_codes, index.list_ids, index.b_sum
            # scan bias = ‖R·c_l‖² + b_sum for L2 (the _ragged_bias_pq
            # formula), b_sum alone for ip metrics
            if self.metric in ("sqeuclidean", "euclidean"):
                rc2 = ivf_pq_mod._center_rot_sqnorm(self.centers,
                                                    self.rotation)
                bias2 = rc2[:, None] + aux2
            else:
                bias2 = aux2
            if index.decoded is None:
                # lazy decode-cache fill (the _search_ragged_pq pattern) —
                # cached back on the index so a later packed strip search
                # reuses it
                index.decoded, index.decoded_scale = ivf_pq_mod._decode_lists(
                    index.codebooks, index.list_codes, pq_dim=index.pq_dim,
                    pq_bits=index.pq_bits,
                    cluster=index.codebook_kind == "cluster")
            extra2 = index.decoded
        else:  # ivf_bq: aux carries the additive term, extra the scale
            payload3, ids2 = index.list_codes, index.list_ids
            aux2 = jnp.where(index.list_ids >= 0, index.list_bias, 0.0)
            bias2 = index.list_bias
            extra2 = index.list_scale
        ids_np = np.asarray(ids2)
        n_lists, max_size = ids_np.shape
        flat_valid = ids_np.reshape(-1) >= 0
        labels_np = np.repeat(np.arange(n_lists, dtype=np.int32), max_size)
        sel = np.nonzero(flat_valid)[0]
        payload = jnp.reshape(payload3, (-1,) + payload3.shape[2:])[sel]
        aux = jnp.reshape(aux2, (-1,))[sel]
        bias = jnp.reshape(bias2, (-1,))[sel]
        extra = None
        if extra2 is not None:
            extra = jnp.reshape(extra2, (-1,) + extra2.shape[2:])[sel]
        self._append(payload, ids_np.reshape(-1)[sel], aux, labels_np[sel],
                     bias, extra)

    # -- introspection ------------------------------------------------------
    @property
    def n_lists(self) -> int:
        return int(self.centers.shape[0])

    @property
    def dim(self) -> int:
        return int(self.centers.shape[1])

    @property
    def capacity_pages(self) -> int:
        return int(self.pages.shape[0])

    @property
    def size(self) -> int:
        """Live (non-tombstoned) rows."""
        with self._lock:
            return len(self._id_loc)

    @property
    def tombstones(self) -> int:
        with self._lock:
            return self._tombstones

    @property
    def pages_used(self) -> int:
        with self._lock:
            return self.capacity_pages - len(self._free)

    @property
    def table_width(self) -> int:
        with self._lock:
            return int(self._table.shape[1])

    @property
    def growth_events(self) -> int:
        """Capacity growths (page pool or table width) since creation —
        each one retraces the scan; steady-state serving should hold at 0."""
        with self._lock:
            return self._growths

    @property
    def mutation_version(self) -> int:
        """Monotonic counter bumped on every committed mutation (append,
        tombstone, growth, compaction swap) — the optimistic-concurrency
        token background compaction validates its snapshot against."""
        with self._lock:
            return self._version

    @property
    def tombstone_ratio(self) -> float:
        """``tombstones / live rows`` — the background-compaction trigger
        signal (``RAFT_TPU_SERVING_COMPACT_RATIO``)."""
        with self._lock:
            return self._tombstones / max(1, len(self._id_loc))

    def list_fill_counts(self) -> np.ndarray:
        """Live (non-tombstoned) rows per list — a copy of the host
        counters append/tombstone maintain incrementally, so the drift
        detector's tick costs O(n_lists), never a pool scan."""
        with self._lock:
            return self._list_live.copy()

    def list_skew(self) -> float:
        """``max / mean`` live rows over all lists — 1.0 is perfectly
        balanced, 0.0 is empty. The maintenance split trigger compares
        this against ``RAFT_TPU_MAINT_SPLIT_SKEW``."""
        counts = self.list_fill_counts()
        total = int(counts.sum())
        if total <= 0:
            return 0.0
        return float(counts.max() * counts.shape[0] / total)

    def stats(self) -> dict:
        with self._lock:
            used = self.pages_used
            return {
                "kind": self.kind, "rows": self.size,
                "tombstones": self._tombstones, "pages_used": used,
                "capacity_pages": self.capacity_pages,
                "page_rows": self.page_rows,
                "table_width": self.table_width,
                "fill_fraction": (self.size / max(1, used * self.page_rows)),
                "tombstone_ratio": (self._tombstones
                                    / max(1, len(self._id_loc))),
                "list_skew": round(self.list_skew(), 4),
                "growth_events": self._growths,
                "mutation_version": self._version,
            }

    def set_filter(self, mask) -> None:
        """Install (or clear, with ``None``) the store's standing predicate.

        ``mask`` is a :class:`~raft_tpu.core.bitset.Bitset` over source-row
        ids, or any boolean/0-1 array convertible to one. Every
        ``search_paged`` call that doesn't pass its own ``filter`` picks
        this one up (a per-call filter takes precedence); ids at or beyond
        the mask length are excluded, so rows upserted after the mask was
        built don't leak through unfiltered.

        Zero-recompile contract: the filter rides the fused search jits as
        a pytree operand whose static aux is only ``n_bits``. Installing
        the FIRST filter (None→Bitset) retraces once, as does changing the
        mask length; mutating mask *contents* at a fixed length re-dispatches
        the same compiled program (tier-1 asserts this via
        ``serving.scan_trace_count()``)."""
        from raft_tpu.core.bitset import Bitset

        if mask is not None and not isinstance(mask, Bitset):
            mask = Bitset.from_mask(jnp.asarray(mask))
        with self._lock:
            self.filter = mask
            self._version += 1
        if obs.enabled():
            obs.add("serving.store.set_filter")

    def device_table(self):
        """Device mirror of the page table (rebuilt only after a table
        mutation — searches between mutations reuse the same array, so
        the scan's operand identity is stable)."""
        with self._lock:
            if self._dev_table is None:
                self._dev_table = jnp.asarray(self._table)
            return self._dev_table

    def scan_state(self):
        """One ATOMIC ``(pages, page_ids, page_aux, table)`` snapshot for
        the paged gather scans. Mutators reassign these arrays under the
        lock; reading them as separate unlocked attribute accesses could
        pair a post-growth table with a pre-growth page pool (a torn
        snapshot that scores candidates against the wrong payload), so
        searches must come through here."""
        with self._lock:
            if self._dev_table is None:
                self._dev_table = jnp.asarray(self._table)
            return self.pages, self.page_ids, self.page_aux, self._dev_table

    def paged_scan_state(self):
        """One ATOMIC snapshot for the paged PALLAS scans:
        ``(payload_pool, bias_pool, scale_pool_or_None, page_ids, table,
        chain_pages)`` — the payload pool is the raw page pool for
        flat/bq and the int8 decoded-residual cache for pq; chain_pages
        is the device mirror of per-list live page counts (a
        scalar-prefetch operand of the kernels)."""
        with self._lock:
            if self._dev_table is None:
                self._dev_table = jnp.asarray(self._table)
            if self._dev_lens is None:
                self._dev_lens = jnp.asarray(self._list_pages)
            payload = self.page_cache if self.kind == "ivf_pq" else self.pages
            return (payload, self.page_bias, self.page_scale, self.page_ids,
                    self._dev_table, self._dev_lens)

    # -- capacity -----------------------------------------------------------
    def _grow_pages(self, min_pages: int) -> None:
        old = self.capacity_pages
        new = old
        while new < min_pages:
            new *= 2
        if new == old:
            return
        pad = new - old
        self.pages = jnp.concatenate(
            [self.pages, jnp.zeros((pad,) + self.pages.shape[1:],
                                   self.pages.dtype)])
        self.page_ids = jnp.concatenate(
            [self.page_ids, jnp.full((pad, self.page_rows), -1, jnp.int32)])
        self.page_aux = jnp.concatenate(
            [self.page_aux, jnp.full((pad, self.page_rows), jnp.inf,
                                     jnp.float32)])
        self.page_bias = jnp.concatenate(
            [self.page_bias, jnp.full((pad, self.page_rows), jnp.inf,
                                      jnp.float32)])
        if self.page_cache is not None:
            self.page_cache = jnp.concatenate(
                [self.page_cache,
                 jnp.zeros((pad,) + self.page_cache.shape[1:], jnp.int8)])
        if self.page_scale is not None:
            self.page_scale = jnp.concatenate(
                [self.page_scale, jnp.zeros((pad, self.page_rows),
                                            jnp.float32)])
        self._fill = np.concatenate([self._fill, np.zeros(pad, np.int32)])
        self._page_list = np.concatenate(
            [self._page_list, np.full(pad, -1, np.int32)])
        self._free.extend(range(old, new))
        self._growths += 1
        self._version += 1
        obs.add("serving.store.capacity_growth")
        resilience.record_event("serving_capacity_growth",
                                pages_from=old, pages_to=new)

    def _grow_table(self, min_width: int) -> None:
        old_w = self.table_width
        new_w = _pow2_at_least(max(min_width, old_w + 1))
        grown = np.full((self.n_lists, new_w), -1, np.int32)
        grown[:, :old_w] = self._table
        self._table = grown
        self._dev_table = None
        self._growths += 1
        self._version += 1
        obs.add("serving.store.table_growth")

    def reserve(self, n_rows: int, skew_factor: int = 4) -> None:
        """Pre-size capacity for ``n_rows`` additional rows, so a serving
        window of known load pays its growth retraces up front, not
        mid-traffic: the page pool for the worst case (every list's tail
        page full), and the page-table width for a ``skew_factor``×-mean
        per-list load (the packed layout's auto-list-cap allowance). A
        stream more skewed than that still grows — and retraces — later."""
        with self._lock:
            need = -(-int(n_rows) // self.page_rows) + self.n_lists
            self._grow_pages(self.pages_used + need)
            total = self.size + int(n_rows)
            mean_rows = -(-total // self.n_lists)
            per_list = -(-mean_rows * skew_factor // self.page_rows) + 1
            # a list already at the current width would widen — and
            # retrace — on its very next page: budget the longest existing
            # chain plus this reservation's worst single-list share
            longest = int(self._list_pages.max()) if self.n_lists else 0
            per_list = max(per_list,
                           longest + -(-int(n_rows) //
                                       (self.n_lists * self.page_rows)) + 1)
            if per_list > self.table_width:
                self._grow_table(per_list)

    # -- allocation (host) --------------------------------------------------
    def _alloc_slots(self, labels_np: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Assign a (page, row) slot to each new row: the owning list's
        tail page while it has room, then fresh pages from the free list.
        Pure host bookkeeping — the device scatter consumes the coords.

        Vectorized per (list, page) rather than per row (a 10M-row ingest
        would otherwise spend minutes in an interpreted loop): rows are
        grouped by label with one stable sort — batch order within each
        list is preserved, so slot assignment is identical to a row-at-a-
        time walk — and each group is carved into contiguous page runs."""
        labels_np = np.asarray(labels_np)
        n = labels_np.shape[0]
        pp = np.empty(n, np.int64)
        rr = np.empty(n, np.int64)
        order = np.argsort(labels_np, kind="stable")
        uniq, starts = np.unique(labels_np[order], return_index=True)
        bounds = np.append(starts[1:], n)
        page_rows = self.page_rows
        for lab, s, e in zip(uniq.tolist(), starts.tolist(), bounds.tolist()):
            idxs = order[s:e]
            cnt = e - s
            pos = 0
            while pos < cnt:
                count = int(self._list_pages[lab])
                tail = int(self._table[lab, count - 1]) if count else -1
                if tail < 0 or self._fill[tail] >= page_rows:
                    if not self._free:
                        self._grow_pages(self.capacity_pages + 1)
                    tail = self._free.pop()
                    if count >= self.table_width:
                        self._grow_table(count + 1)
                    self._table[lab, count] = tail
                    self._list_pages[lab] = count + 1
                    self._page_list[tail] = lab
                    self._dev_table = None
                    self._dev_lens = None
                take = min(cnt - pos, page_rows - int(self._fill[tail]))
                sel = idxs[pos:pos + take]
                pp[sel] = tail
                rr[sel] = int(self._fill[tail]) + np.arange(take)
                self._fill[tail] += take
                pos += take
        return pp, rr

    # -- mutation -----------------------------------------------------------
    def _assign_labels(self, work) -> np.ndarray:
        km_metric = ("inner_product"
                     if self.metric in ("cosine", "inner_product")
                     else "sqeuclidean")
        labels = kmeans_balanced.predict(
            work, self.centers,
            kmeans_balanced.KMeansBalancedParams(metric=km_metric),
            res=self._res)
        return np.asarray(labels)

    def _prepare_payload(self, work, labels_np):
        """(payload, aux, bias, extra) rows for the store's page pools —
        the same math the packed build applies, so compact()/parity hold
        bitwise. ``bias`` is the scan-bias pool row (the packed kernels'
        per-entry additive term), ``extra`` the kind-specific second pool
        row (PQ decoded cache / BQ scale) or None."""
        l2 = self.metric in ("sqeuclidean", "euclidean")
        if self.kind == "ivf_flat":
            if jnp.issubdtype(self.pages.dtype, jnp.integer):
                info = jnp.iinfo(self.pages.dtype)
                payload = jnp.clip(jnp.round(work), info.min, info.max) \
                    .astype(self.pages.dtype)
            else:
                payload = work.astype(self.pages.dtype)
            if l2:
                aux = _flat_row_aux(payload)
            else:
                aux = jnp.zeros((work.shape[0],), jnp.float32)
            return payload, aux, aux, None
        if self.kind == "ivf_bq":
            labels = jnp.asarray(labels_np)
            rc = linalg.rotate_rows(self.centers, self.rotation,
                                    self.rotation_kind)
            c2 = dist_mod.sqnorm(self.centers)
            payload, scale, bias = ivf_bq_mod._encode_chunk(
                work, labels, self.centers, self.rotation, rc, c2, l2,
                self.bq_bits, self.rotation_kind)
            return payload, bias, bias, scale
        labels = jnp.asarray(labels_np)
        resid = ivf_pq_mod._pad_rot(work - self.centers[labels],
                                    self.rotation.shape[0]) @ self.rotation.T
        dsub = self.codebooks.shape[2]
        resid3 = resid.reshape(work.shape[0], self.pq_dim, dsub)
        codes = ivf_pq_mod._encode(resid3, self.codebooks)
        payload = ivf_pq_mod.pack_codes(codes, self.pq_bits)
        if l2:
            aux = ivf_pq_mod._row_b_sum(
                self.centers, self.rotation, self.codebooks, payload, labels,
                self.pq_dim, self.pq_bits)
            # scan bias = ‖R·c_l‖² + b_sum — the _ragged_bias_pq formula,
            # applied per row at its label
            rc2 = ivf_pq_mod._center_rot_sqnorm(self.centers, self.rotation)
            bias = rc2[labels] + aux
        else:
            # inner-product metrics carry no list-side term (the packed
            # b_sum is zeros at valid entries)
            aux = jnp.zeros((work.shape[0],), jnp.float32)
            bias = aux
        # the paged Pallas scan's MXU operand: int8 decoded-residual rows,
        # bit-identical to the packed decode of the same codes
        extra = ivf_pq_mod._decode_code_rows(
            self.codebooks, payload, self.decoded_scale, self.pq_dim,
            self.pq_bits)
        return payload, aux, bias, extra

    @traced("serving::upsert")
    def upsert(self, vectors, ids=None) -> dict:
        """Insert (or replace, by id) rows: assign each to its nearest
        centroid and append to that list's tail page. No repacking — the
        page pool/table shapes are untouched unless capacity itself grows.

        Returns ``{"upserts": n, "replaced": r, "growths": g}``.
        """
        vectors = jnp.asarray(vectors)
        if vectors.ndim != 2 or vectors.shape[1] != self.dim:
            raise ValueError(
                f"vectors must be (n, {self.dim}), got {vectors.shape}")
        n = int(vectors.shape[0])
        if n == 0:
            return {"upserts": 0, "replaced": 0, "growths": 0}
        work = vectors.astype(jnp.float32)
        if self.metric == "cosine":
            work = work / jnp.maximum(
                jnp.linalg.norm(work, axis=1, keepdims=True), 1e-30)
        if ids is not None:
            ids_np = np.asarray(ids, np.int64)
            if ids_np.shape != (n,):
                raise ValueError(f"ids must be ({n},), got {ids_np.shape}")
            if len(set(ids_np.tolist())) != n:
                raise ValueError("duplicate ids within one upsert batch")
            if ids_np.min() < 0 or ids_np.max() >= 2**31 - 1:
                raise ValueError("ids must fit int32 and be >= 0")

        labels_np = self._assign_labels(work)
        payload, aux, bias, extra = self._prepare_payload(work, labels_np)

        with self._lock:
            if ids is None:
                # auto-id generation INSIDE the lock: reading max(_id_loc)
                # before it races a concurrent upsert into minting the
                # same ids twice (silent replacement of the other batch)
                start = (max(self._id_loc) + 1) if self._id_loc else 0
                ids_np = np.arange(start, start + n, dtype=np.int64)
                if ids_np.max() >= 2**31 - 1:
                    raise ValueError("ids must fit int32 and be >= 0")
            # replaced ids: capture the OLD slots now, tombstone them only
            # AFTER the append lands — tombstoning first would turn a
            # failed append (FATAL fault, dispatch error) into silent data
            # loss of the previous versions. The append overwrites the id
            # map, so a search between commit points sees the new rows.
            old_locs = [self._id_loc[int(i)] for i in ids_np
                        if int(i) in self._id_loc]
            replaced = len(old_locs)
            g0 = self._growths
            done = [0]  # survives degrade retries: landed chunks stay landed

            def append_chunk(chunk_rows: int):
                while done[0] < n:
                    resilience.faultpoint("serving.store.upsert")
                    s = done[0]
                    e = min(n, s + chunk_rows)
                    self._append(payload[s:e], ids_np[s:e], aux[s:e],
                                 labels_np[s:e], bias[s:e],
                                 None if extra is None else extra[s:e])
                    done[0] = e
                return n

            # OOM-degraded append: a too-large scatter chunk halves down
            # to a page at a time (standing gate: every failure-prone
            # dispatch path recovers or classifies)
            resilience.degrade_on_oom(
                append_chunk, max(n, 1), floor=min(n, self.page_rows) or 1,
                site="serving.store.upsert")
            if old_locs:
                self._tombstone_slots(old_locs)
            growths = self._growths - g0
        if obs.enabled():
            obs.add("serving.store.upserts", n)
            if replaced:
                obs.add("serving.store.replaced", replaced)
            # roofline note (round 15): the scatter is pure data movement
            # (flops=0 → memory-bound by construction); the model prices
            # the pow2 bucket the dispatch actually pays
            from raft_tpu.obs import roofline as obs_roofline

            extra_bytes = 0
            if self.kind == "ivf_pq":
                extra_bytes = self._cache_dim     # int8 decoded-cache row
            elif self.kind == "ivf_bq":
                extra_bytes = 4                   # fp32 scale row
            obs_roofline.note_dispatch(
                "serving.scatter",
                {"n_rows": n, "dim": self.dim,
                 "payload_width": int(self.pages.shape[2]),
                 "payload_dtype": str(self.pages.dtype),
                 "extra_row_bytes": extra_bytes})
        return {"upserts": n, "replaced": replaced, "growths": growths}

    def _append(self, payload, ids_np, aux, labels_np, bias, extra) -> None:
        """Allocate slots and scatter one chunk (lock held). The scatter
        is padded to a power-of-two row count so a lifetime of arbitrary
        upsert batch sizes compiles O(log max_batch) programs, not one
        per distinct size."""
        m = int(payload.shape[0])
        if m == 0:
            return
        ids_np = np.asarray(ids_np, np.int64)
        pp, rr = self._alloc_slots(np.asarray(labels_np))
        ids_dev = jnp.asarray(ids_np)
        bucket = _pow2_at_least(m)
        if bucket != m:
            pad = bucket - m
            # sentinel page == capacity: out of bounds, mode="drop"
            pp = np.concatenate([pp, np.full(pad, self.capacity_pages)])
            rr = np.concatenate([rr, np.zeros(pad, np.int64)])
            payload = jnp.concatenate([payload, jnp.zeros(
                (pad,) + payload.shape[1:], payload.dtype)])
            ids_dev = jnp.concatenate(
                [ids_dev, jnp.zeros((pad,), ids_dev.dtype)])
            aux = jnp.concatenate([aux, jnp.zeros((pad,), aux.dtype)])
            bias = jnp.concatenate([bias, jnp.zeros((pad,), bias.dtype)])
            if extra is not None:
                extra = jnp.concatenate([extra, jnp.zeros(
                    (pad,) + extra.shape[1:], extra.dtype)])
        extra_pool = (self.page_cache if self.kind == "ivf_pq"
                      else self.page_scale)
        pages, page_ids, page_aux, page_bias, extra_pool = _scatter_rows(
            self.pages, self.page_ids, self.page_aux, self.page_bias,
            extra_pool, payload, ids_dev.astype(jnp.int32),
            aux.astype(jnp.float32), bias.astype(jnp.float32),
            extra, jnp.asarray(pp), jnp.asarray(rr))
        # commit device state first, host map second: a raise above leaves
        # the store exactly as it was (slots burned in _fill are padding)
        self.pages, self.page_ids, self.page_aux = pages, page_ids, page_aux
        self.page_bias = page_bias
        if self.kind == "ivf_pq":
            self.page_cache = extra_pool
        elif self.kind == "ivf_bq":
            self.page_scale = extra_pool
        for i in range(m):
            self._id_loc[int(ids_np[i])] = (int(pp[i]), int(rr[i]))
        np.add.at(self._list_live, np.asarray(labels_np, np.int64)[:m], 1)
        self._version += 1

    def _tombstone_slots(self, locs: List[Tuple[int, int]]) -> None:
        """Mark (page, row) slots dead in place (lock held): ``page_ids``
        -1 and ``page_bias`` +inf there, in one dispatch. Slots are never
        reused — compact() reclaims them."""
        pp = np.array([p for p, _ in locs], np.int64)
        rr = np.array([r for _, r in locs], np.int64)
        labs = self._page_list[pp]
        np.subtract.at(self._list_live, labs[labs >= 0], 1)
        bucket = _pow2_at_least(len(locs))
        if bucket != len(locs):
            pad = bucket - len(locs)
            pp = np.concatenate([pp, np.full(pad, self.capacity_pages)])
            rr = np.concatenate([rr, np.zeros(pad, np.int64)])
        self.page_ids, self.page_bias = _tombstone(
            self.page_ids, self.page_bias, jnp.asarray(pp), jnp.asarray(rr))
        self._tombstones += len(locs)
        self._version += 1

    def _tombstone_ids(self, present: List[int]) -> int:
        """Tombstone rows by id and drop them from the id map (lock held)."""
        if not present:
            return 0
        self._tombstone_slots([self._id_loc[i] for i in present])
        for i in present:
            del self._id_loc[i]
        return len(present)

    @traced("serving::delete")
    def delete(self, ids) -> int:
        """Tombstone rows by id; unknown ids are ignored. Returns the
        number of rows actually removed."""
        ids_np = np.asarray(ids).reshape(-1)
        with self._lock:
            removed = self._tombstone_ids(
                [int(i) for i in ids_np if int(i) in self._id_loc])
        if obs.enabled() and removed:
            obs.add("serving.store.deletes", removed)
        return removed

    # -- compaction ---------------------------------------------------------
    def _live_rows(self):
        """(payload, aux, extra, ids, labels) of live rows in per-list
        chain order — the arrival order, which is what a from-scratch pack
        over the same rows produces (pack_lists' label argsort is stable).

        Only the SNAPSHOT is taken under the lock (host tables copied,
        immutable device arrays referenced); the gathers run on the
        snapshot outside it, so a long compaction never stalls the
        upsert/delete hot path (round-16 off-hot-path contract)."""
        with self._lock:
            table = self._table.copy()
            list_pages = self._list_pages.copy()
            fill = self._fill.copy()
            page_list = self._page_list.copy()
            pages, page_ids = self.pages, self.page_ids
            page_aux, page_scale = self.page_aux, self.page_scale
        perm = []
        for lab in range(self.n_lists):
            for p in table[lab, :list_pages[lab]]:
                base = int(p) * self.page_rows
                perm.extend(range(base, base + int(fill[p])))
        perm = np.asarray(perm, np.int64)
        ids_flat = np.asarray(page_ids).reshape(-1)
        labels_flat = np.repeat(page_list, self.page_rows)
        if perm.size:
            ids_sel = ids_flat[perm]
            live = ids_sel >= 0
            perm = perm[live]
            ids_sel = ids_sel[live]
            labels_sel = labels_flat[perm]
        else:
            ids_sel = np.empty(0, np.int32)
            labels_sel = np.empty(0, np.int32)
        payload_flat = jnp.reshape(pages, (-1,) + pages.shape[2:])
        payload = jnp.take(payload_flat, jnp.asarray(perm), axis=0)
        aux = jnp.take(jnp.reshape(page_aux, (-1,)),
                       jnp.asarray(perm), axis=0)
        extra = None
        if page_scale is not None:
            extra = jnp.take(jnp.reshape(page_scale, (-1,)),
                             jnp.asarray(perm), axis=0)
        return (payload, aux, extra, ids_sel.astype(np.int32),
                labels_sel.astype(np.int32))

    @traced("serving::compact")
    def compact(self):
        """Fold the live rows back into the packed representation: an
        ``IvfFlatIndex`` / ``IvfPqIndex`` / ``IvfBqIndex`` over exactly
        the surviving rows, with the store's frozen quantizers. The result
        serializes through the v2 snapshot container (``index.save``) —
        that is the paged store's durable form. The per-row aux (norms /
        b_sum / bq bias+scale) is CARRIED, not recomputed: recomputing
        over the packed shape can flip low mantissa bits (different
        reduction tiling) and break the compacted-scan ↔ paged-scan value
        parity the tier-1 tests pin.

        Only the row snapshot holds the store lock; the fold itself runs
        on immutable snapshot arrays, so compaction is concurrency-safe
        against (and invisible to) in-flight searches and mutations —
        :meth:`compact_swap` re-validates against ``mutation_version``
        before any state is replaced."""
        payload, aux, extra, ids_np, labels_np = self._live_rows()
        # strip-eligible granule (round 16): the compacted snapshot feeds
        # the packed strip/BQ kernels directly (512-pow2 list padding is
        # what strip_eligible demands); gather consumers are indifferent
        group = 512
        ids_dev = jnp.asarray(ids_np)
        labels_dev = jnp.asarray(labels_np)
        list_payload, list_ids = pack_lists(
            payload, ids_dev, labels_dev, self.n_lists, group,
            pow2_chunks=True)
        # same stable label-argsort permutation as the payload pack
        if self.kind == "ivf_bq":
            aux2, _ = pack_lists(jnp.stack([extra, aux], axis=1), ids_dev,
                                 labels_dev, self.n_lists, group,
                                 pow2_chunks=True)
            out = ivf_bq_mod.IvfBqIndex(
                self.centers, self.rotation, list_payload, list_ids,
                aux2[:, :, 0],
                jnp.where(list_ids >= 0, aux2[:, :, 1], jnp.inf),
                self.metric, self.bq_bits, self.rotation_kind)
        else:
            aux_packed, _ = pack_lists(aux, ids_dev, labels_dev,
                                       self.n_lists, group,
                                       pow2_chunks=True)
            if self.kind == "ivf_flat":
                norms = None
                if self.metric in ("sqeuclidean", "euclidean"):
                    norms = aux_packed
                out = ivf_flat_mod.IvfFlatIndex(
                    self.centers, list_payload, list_ids, norms,
                    self.metric, group)
            else:
                # packed convention: +inf at padding so the scan self-masks
                b_sum = jnp.where(list_ids >= 0, aux_packed, jnp.inf)
                out = ivf_pq_mod.IvfPqIndex(
                    self.centers, self.rotation, self.codebooks,
                    list_payload, list_ids, b_sum, None, self.metric,
                    self.pq_bits, group, codebook_kind=self.codebook_kind,
                    pq_dim_hint=self.pq_dim)
        if obs.enabled():
            obs.add("serving.store.compactions")
        return out

    def _empty_clone(self, centers=None) -> "PagedListStore":
        """A row-free store with the SAME quantizers, page height, pool
        capacity and table width — the staging target a background
        compaction repages into before the atomic swap (same capacity ⇒
        same operand shapes ⇒ the swap never retraces the scans).

        ``centers`` (same shape/dtype) replaces the coarse centroids for
        a maintenance re-clustering clone: the centers operand keeps its
        shape, so the coarse gemm re-dispatches its compiled program."""
        if centers is None:
            centers = self.centers
        else:
            centers = jnp.asarray(centers, self.centers.dtype)
            if centers.shape != self.centers.shape:
                raise ValueError(
                    f"replacement centers must be {self.centers.shape}, "
                    f"got {centers.shape}")
        with self._lock:
            # one consistent (pool, capacity, width) triple — unlocked
            # property reads could pair a post-growth width with a
            # pre-growth capacity and stage a retracing clone
            pages = self.pages
            cap = self.capacity_pages
            width = self.table_width
        clone = PagedListStore(
            self.kind, centers, self.metric, page_rows=self.page_rows,
            payload_width=int(pages.shape[2]),
            payload_dtype=pages.dtype, rotation=self.rotation,
            codebooks=self.codebooks, pq_bits=self.pq_bits,
            pq_dim=self.pq_dim, codebook_kind=self.codebook_kind,
            bq_bits=self.bq_bits, rotation_kind=self.rotation_kind,
            initial_pages=cap, res=self._res)
        if clone.table_width < width:
            clone._table = np.full((self.n_lists, width), -1, np.int32)
        return clone

    _SWAP_FIELDS = ("pages", "page_ids", "page_aux", "page_bias",
                    "page_cache", "page_scale", "_table", "_list_pages",
                    "_fill", "_page_list", "_free", "_id_loc", "_list_live")

    def _adopt_clone(self, clone: "PagedListStore", expected_version: int,
                     tag: str) -> bool:
        """The one atomic-swap critical section compaction and maintenance
        share: re-validate ``mutation_version`` against
        ``expected_version`` (a mutation that landed after the caller's
        snapshot aborts — returns False, nothing changed, counted as
        ``serving.store.<tag>_stale``), refuse a clone whose staging grew
        the operand shapes (``<tag>_regrown``), then adopt the clone's
        pools, host tables AND centers wholesale. Centers adoption is what
        lets a re-clustering swap move centroids without touching the
        compiled scan layout — same shapes, new values."""
        with self._lock:
            if self._version != int(expected_version):
                obs.add(f"serving.store.{tag}_stale")
                return False
            if (clone.capacity_pages != self.capacity_pages
                    or clone.table_width != self.table_width):
                # the staging itself grew (a pathological fill pattern):
                # adopting it would change operand shapes mid-serving, so
                # refuse — the caller retries after its next snapshot
                obs.add(f"serving.store.{tag}_regrown")
                return False
            for name in self._SWAP_FIELDS:
                setattr(self, name, getattr(clone, name))
            self.centers = clone.centers
            self._tombstones = 0
            self._dev_table = None
            self._dev_lens = None
            self._version += 1
        return True

    def compact_swap(self, compacted, expected_version: int) -> bool:
        """Adopt a compacted index as this store's new paged state:
        live rows re-paged front-to-back (tombstone slots reclaimed into
        the free list), capacity and table width UNCHANGED (so the paged
        scans re-dispatch their compiled programs — zero recompiles).

        The repage runs on a staging clone OFF the lock; the final swap is
        one short critical section that first re-validates
        ``mutation_version`` against ``expected_version`` — a mutation
        that landed after the caller's :meth:`compact` snapshot aborts the
        swap (returns False, nothing changed) rather than losing it.
        In-flight searches hold their own array snapshots
        (:meth:`scan_state` / :meth:`paged_scan_state`) and are untouched
        either way."""
        clone = self._empty_clone()
        clone._ingest_packed(compacted)
        if not self._adopt_clone(clone, expected_version, "compact_swap"):
            return False
        if obs.enabled():
            obs.add("serving.store.compact_swaps")
        return True

    def recluster_swap(self, clone: "PagedListStore",
                       expected_version: int) -> bool:
        """Adopt a maintenance staging clone — same capacity, table width
        and operand shapes, possibly NEW centers — atomically. The clone
        must hold the FULL surviving row set (the maintenance cycle stages
        every live row, re-encoded only where its assignment moved);
        racing mutations abort exactly like :meth:`compact_swap` and the
        caller classifies the ``stale`` outcome."""
        if not self._adopt_clone(clone, expected_version, "recluster_swap"):
            return False
        if obs.enabled():
            obs.add("serving.store.recluster_swaps")
        return True

    def _ingest_rows(self, payload, ids_np, aux, labels_np, bias, extra,
                     chunk_rows: int = 65536) -> None:  # holds: _lock
        """Construction-phase bulk append for maintenance staging clones:
        pre-encoded rows arrive in final per-list order (the caller's
        snapshot order) and land through the same pow2-bucketed scatter as
        serving upserts, chunked so one giant ingest never compiles a
        bucket far above the serving sizes. Callers own exclusivity — the
        clone is unpublished, the :meth:`_ingest_packed` contract."""
        n = int(np.asarray(ids_np).shape[0])
        for s in range(0, n, int(chunk_rows)):
            e = min(n, s + int(chunk_rows))
            self._append(payload[s:e], ids_np[s:e], aux[s:e],
                         labels_np[s:e], bias[s:e],
                         None if extra is None else extra[s:e])

    def restore_shape(self, capacity_pages: int, table_width: int) -> None:
        """Pre-grow to a previously captured ``(capacity_pages,
        table_width)`` — the page plan the capacity plane preserves across
        a tier round-trip, so a promoted store re-dispatches the same
        compiled scan programs it had before demotion instead of paying
        the growth retraces again mid-traffic."""
        with self._lock:
            if int(capacity_pages) > self.capacity_pages:
                self._grow_pages(int(capacity_pages))
            if int(table_width) > self.table_width:
                self._grow_table(int(table_width))
            # materialize the device table mirror eagerly: promotion is
            # the off-path moment to pay the transfer, not the first
            # post-promote search (and the capacity ledger's predicted
            # footprint counts the mirror unconditionally)
            if self._dev_table is None:
                self._dev_table = jnp.asarray(self._table)
