"""Multi-tenant capacity plane: acting admission + tiered residency.

ROADMAP item 4's control half (ISSUE 15). Round 14 gave the repo exact
per-index HBM prediction and classified ADMIT/QUEUE/REJECT verdicts
(``obs.costmodel.check_admission``) — but the verdicts were record-only
gauges, and the only memory policy that *acted* was still OOM-then-halve
after the fact. This module makes the verdicts binding ("Memory Safe
Computations with XLA", PAPERS.md: act on the static model BEFORE
dispatch, and oversubscription degrades instead of OOMing):

* :class:`TenantRegistry` — named index/store namespaces, each with a
  **residency tier**:

  ======  ==========================================================
  HOT     full index resident (plus the warm codes); exact serving
  WARM    only the BQ sign codes resident (~32× compression of the
          fp32 rows); serves **degraded** (no-refine BQ recall, the
          result carries ``degraded=True``); the v2 snapshot on disk
          is the rerank/promote source
  COLD    v2 snapshot only — nothing resident; first query pages the
          warm codes back in (admission-checked), full promotion is
          the explicit/measured hot-swap
  ======  ==========================================================

  The warm twin is built ONCE at registration (off the serving path)
  and stays resident while the tenant is HOT, so demotion under
  pressure is an instant drop of the hot arrays — never an index build
  on the eviction path.

* :class:`CapacityController` — the budgeter + acting admission
  controller. Every tenant dispatch projects its
  ``costmodel.estimate_search`` transient against the **predicted
  resident bytes** of the whole registry (deterministic accounting: the
  capacity plane manages what it registered) and the HBM budget:

  - **ADMIT** dispatches;
  - **QUEUE** serves the warm tier degraded when the codes are resident,
    else holds under the caller's existing
    :class:`~raft_tpu.resilience.Deadline` (expiry → classified
    DEADLINE, never a hang);
  - **REJECT** sizes an eviction from the verdict's ``shortfall_bytes``
    (round-18 satellite on ``check_admission``), demotes
    least-recently-served tenants tier-down to free exactly that many
    predicted bytes, re-checks, and only then rejects classified
    (:class:`CapacityRejected`; the :class:`QueryQueue` wiring lands it
    as the ``rejected`` request verdict).

  Demotions are bounded per window (``RAFT_TPU_CAPACITY_MAX_DEMOTIONS``
  per ``RAFT_TPU_CAPACITY_WINDOW_S``) so alternating pressure cannot
  livelock the registry into demote/promote thrash. Promotion
  (:meth:`~CapacityController.promote`) restores the snapshot through
  the faultpointed ``serving.capacity.promote`` site under its own
  deadline (``RAFT_TPU_CAPACITY_PROMOTE_DEADLINE_S``) with the measured
  hot-swap latency recorded — a failed or injected-fault promote leaves
  the tenant in its prior tier, classified.

Per-tenant verdict counts, residency bytes and SLO rows ride
``obs.report.collect(capacity=controller)``; the bench's chaos rung
(``bench.py`` ``capacity`` section) serves N tenants ~4× oversubscribed
on a synthetic budget and gates zero OOM verdicts.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from raft_tpu import obs, resilience
from raft_tpu.obs import costmodel
from raft_tpu.resilience.retry import record_event

__all__ = [
    "COLD",
    "HOT",
    "MAX_DEMOTIONS_ENV",
    "PROMOTE_DEADLINE_ENV",
    "WARM",
    "WINDOW_ENV",
    "CapacityController",
    "CapacityRejected",
    "Tenant",
    "TenantRegistry",
    "TenantResult",
    "default_max_demotions",
    "default_promote_deadline",
    "default_window_s",
]

HOT, WARM, COLD = "hot", "warm", "cold"
TIERS = (HOT, WARM, COLD)

MAX_DEMOTIONS_ENV = "RAFT_TPU_CAPACITY_MAX_DEMOTIONS"
WINDOW_ENV = "RAFT_TPU_CAPACITY_WINDOW_S"
PROMOTE_DEADLINE_ENV = "RAFT_TPU_CAPACITY_PROMOTE_DEADLINE_S"

#: request verdict the QueryQueue stamps on a capacity-rejected request —
#: a FIRST-CLASS classified outcome (obs/report counts it as known, never
#: unclassified residue)
REJECTED = "rejected"


def _env_pos(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        v = float(raw) if raw else default
    except ValueError:
        v = default
    return max(v, 0.0)


def default_max_demotions() -> int:
    """Demotions allowed per window (anti-thrash bound; the satellite
    livelock property test pins it)."""
    return int(_env_pos(MAX_DEMOTIONS_ENV, 8))


def default_window_s() -> float:
    """The demotion-rate window in seconds."""
    return _env_pos(WINDOW_ENV, 1.0) or 1.0


def default_promote_deadline() -> float:
    """Wall-clock bound on one snapshot restore (promotion); a hang on
    the tunneled runtime lands as a classified DEADLINE verdict."""
    return _env_pos(PROMOTE_DEADLINE_ENV, 30.0) or 30.0


class CapacityRejected(RuntimeError):
    """A dispatch the admission controller refused after attempting an
    eviction: the predicted footprint does not fit the budget even with
    least-recently-served tenants demoted. First-class ``rejected``
    verdict — NOT an OOM (the whole point is that the device allocator
    never saw the dispatch)."""


class TenantResult(tuple):
    """A ``(distances, indices)`` pair with tiering metadata riding
    along (the distributed ``SearchResult`` shape): unpacks as the plain
    2-tuple; degraded-mode consumers read ``degraded`` / ``tier`` /
    ``tenant``. Warm-tier results ALWAYS carry ``degraded=True`` — the
    shadow/SLO plane is what attributes the recall hit."""

    def __new__(cls, distances, indices, tenant: str, tier: str,
                degraded: bool = False):
        self = tuple.__new__(cls, (distances, indices))
        self.tenant = str(tenant)
        self.tier = str(tier)
        self.degraded = bool(degraded)
        return self

    @property
    def distances(self):
        return self[0]

    @property
    def indices(self):
        return self[1]


# ---------------------------------------------------------------------------
# tenants + registry
# ---------------------------------------------------------------------------


class Tenant:
    """One named namespace: the resident objects per tier, their
    predicted byte costs, the snapshot paths, and serving stats."""

    def __init__(self, name: str, kind: str, snapshot_dir: str):
        self.name = name
        self.kind = kind
        self.snapshot_dir = snapshot_dir
        # the tenant's own leaf lock: serving threads bump stats while the
        # promotion worker swaps tiers — every multi-field transition goes
        # through the mutator methods below. Registration-time writes in
        # TenantRegistry.register happen before the tenant is published
        # (construction phase; the registry dict insert is the barrier).
        self._lock = threading.Lock()
        self.tier = HOT                # guarded-by: _lock, reads-ok
        self.hot_obj = None            # guarded-by: _lock, reads-ok -- full index / paged store
        self.warm_index = None         # guarded-by: _lock, reads-ok -- IvfBqIndex (codes-only twin)
        self.warm_enabled = False      # tenant HAS a warm tier at all
        self.warm_ids: Optional[np.ndarray] = None  # guarded-by: _lock, reads-ok -- warm pos -> id
        self.hot_bytes = 0             # guarded-by: _lock, reads-ok -- predicted bytes of hot_obj
        self.warm_bytes = 0            # guarded-by: _lock, reads-ok -- predicted bytes of the twin
        self.search_fn: Optional[Callable] = None   # guarded-by: _lock, reads-ok
        self.last_served = 0.0         # guarded-by: _lock, reads-ok -- monotonic; the LRU key
        self.last_demoted = 0.0        # guarded-by: _lock, reads-ok
        self.serves = 0                # guarded-by: _lock, reads-ok
        self.degraded_serves = 0       # guarded-by: _lock, reads-ok
        self.demotions = 0             # guarded-by: _lock, reads-ok
        self.promotions = 0            # guarded-by: _lock, reads-ok
        self.verdicts: Dict[str, int] = {}   # guarded-by: _lock
        self.outcomes: Dict[str, int] = {}   # guarded-by: _lock -- ok/rejected/... counts
        self.lats: deque = deque(maxlen=256)  # guarded-by: _lock -- served latencies (s)
        # mutability across the tier cycle (paged-store tenants only):
        # WARM/COLD upserts buffer here and replay on promote; the page
        # plan preserves the store's compiled-shape envelope over the
        # demote→promote round trip (zero growth retraces mid-traffic)
        self.pending: list = []        # guarded-by: _lock -- [(rows f32, ids i64)] in arrival order
        self.pending_deletes: set = set()  # guarded-by: _lock -- ids whose latest op is a delete
        self.pending_rows = 0          # guarded-by: _lock, reads-ok
        self.page_plan: Optional[dict] = None  # guarded-by: _lock, reads-ok -- snapshot page layout

    # -- mutators (the only post-publication writers) -----------------------

    def touch(self) -> None:
        """Stamp the LRU eviction key with 'served now'."""
        with self._lock:
            self.last_served = time.monotonic()

    def record_verdict(self, verdict: str) -> None:
        with self._lock:
            self.verdicts[verdict] = self.verdicts.get(verdict, 0) + 1

    def record_serve(self, dt: float) -> None:
        """One successful hot/warm serve: count, outcome, latency sample."""
        with self._lock:
            self.serves += 1
            self.outcomes["ok"] = self.outcomes.get("ok", 0) + 1
            self.lats.append(dt)

    def record_outcome(self, outcome: str) -> None:
        with self._lock:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1

    def record_degraded(self) -> None:
        with self._lock:
            self.degraded_serves += 1

    def set_search_fn(self, fn: Optional[Callable]) -> None:
        with self._lock:
            self.search_fn = fn

    def adopt_warm(self, warm, ids, warm_bytes: int) -> None:
        """Install loaded warm codes (COLD tenants step up to WARM)."""
        with self._lock:
            self.warm_index = warm
            self.warm_ids = ids
            self.warm_bytes = int(warm_bytes)
            if self.tier == COLD:
                self.tier = WARM

    def adopt_hot(self, hot, hot_bytes: int) -> None:
        """Install a promoted hot object: tier up + count the promotion."""
        with self._lock:
            self.hot_obj = hot
            self.hot_bytes = int(hot_bytes)
            self.tier = HOT
            self.promotions += 1

    # -- mutability across the tier cycle -----------------------------------

    def apply_upsert(self, vectors, ids=None) -> dict:
        """Accept an upsert at ANY tier. HOT applies straight to the live
        paged store (under the tenant lock, so a concurrent demotion's
        hibernation snapshot can never lose the rows); WARM/COLD buffers
        the batch for replay at the next promote — those rows still serve
        (exactly) through the warm tier's pending merge. Buffered rows
        REQUIRE explicit ids: auto-assignment is only stable against the
        live store."""
        rows = np.asarray(vectors, dtype=np.float32)
        if rows.ndim != 2:
            raise ValueError(f"vectors must be 2-D, got shape {rows.shape}")
        with self._lock:
            if self.tier == HOT and self.hot_obj is not None:
                if not hasattr(self.hot_obj, "upsert"):
                    raise TypeError(
                        f"tenant {self.name!r} ({self.kind}) serves a "
                        f"packed index — register a paged store for live "
                        f"mutation")
                self.hot_obj.upsert(rows, ids)
                return {"tier": HOT, "applied": int(rows.shape[0]),
                        "buffered": 0}
            if self.kind != "paged_store":
                raise TypeError(
                    f"tenant {self.name!r} ({self.kind}) is immutable — "
                    f"only paged-store tenants accept upserts across the "
                    f"tier cycle")
            if ids is None:
                raise ValueError(
                    f"tenant {self.name!r} is {self.tier} — buffered "
                    f"upserts require explicit ids")
            ids_np = np.asarray(ids, dtype=np.int64).reshape(-1)
            if ids_np.shape[0] != rows.shape[0]:
                raise ValueError(
                    f"ids shape {ids_np.shape} does not match "
                    f"{rows.shape[0]} rows")
            # an upsert supersedes any earlier buffered delete of its id
            self.pending_deletes.difference_update(ids_np.tolist())
            self.pending.append((rows, ids_np))
            self.pending_rows += int(rows.shape[0])
            return {"tier": self.tier, "applied": 0,
                    "buffered": int(rows.shape[0])}

    def apply_delete(self, ids) -> dict:
        """Delete at ANY tier: HOT tombstones in the live store; WARM/COLD
        drops matching buffered rows and records the ids for replay."""
        ids_np = np.unique(np.asarray(ids, dtype=np.int64).reshape(-1))
        with self._lock:
            if self.tier == HOT and self.hot_obj is not None:
                if not hasattr(self.hot_obj, "delete"):
                    raise TypeError(
                        f"tenant {self.name!r} ({self.kind}) serves a "
                        f"packed index — register a paged store for live "
                        f"mutation")
                removed = int(self.hot_obj.delete(ids_np))
                return {"tier": HOT, "removed": removed, "buffered": 0}
            if self.kind != "paged_store":
                raise TypeError(
                    f"tenant {self.name!r} ({self.kind}) is immutable — "
                    f"only paged-store tenants accept deletes across the "
                    f"tier cycle")
            dropped = 0
            batches = []
            for rows, bids in self.pending:
                keep = ~np.isin(bids, ids_np)
                dropped += int(bids.size - keep.sum())
                if keep.all():
                    batches.append((rows, bids))
                elif keep.any():
                    batches.append((rows[keep], bids[keep]))
            self.pending = batches
            self.pending_rows -= dropped
            self.pending_deletes.update(ids_np.tolist())
            return {"tier": self.tier, "removed": dropped,
                    "buffered": int(ids_np.size)}

    def pending_view(self) -> Optional[tuple]:
        """Deduplicated snapshot of the buffered mutations for the warm
        tier's exact merge: ``(rows, ids, deletes)`` with keep-LAST id
        semantics (a later upsert supersedes); None when nothing is
        pending."""
        with self._lock:
            if not self.pending and not self.pending_deletes:
                return None
            batches = list(self.pending)
            deletes = set(self.pending_deletes)
        if batches:
            rows = np.concatenate([b[0] for b in batches])
            ids_np = np.concatenate([b[1] for b in batches])
            _, last_rev = np.unique(ids_np[::-1], return_index=True)
            keep = np.sort(ids_np.size - 1 - last_rev)
            rows, ids_np = rows[keep], ids_np[keep]
        else:
            rows = ids_np = None
        return rows, ids_np, deletes

    def drain_pending(self) -> tuple:
        """Atomically take (and clear) the buffered mutations —
        ``(batches, deletes)`` for replay into a freshly promoted store.
        Upserts replay in arrival order before the deletes (the buffer
        invariants make that ordering exact: an id in ``deletes`` has no
        buffered row, and a re-upserted id left ``deletes`` on arrival)."""
        with self._lock:
            batches = self.pending
            deletes = sorted(self.pending_deletes)
            self.pending = []
            self.pending_deletes = set()
            self.pending_rows = 0
        return batches, deletes

    def demote_one_tier(self, now: float, snapshot_cb=None) -> Optional[dict]:
        """One atomic tier-down transition; returns the demotion record
        (None when the tenant already holds nothing). HOT drops the full
        index (warm codes stay resident — the instant path); WARM drops
        the codes. ``snapshot_cb(hot_obj)`` runs BEFORE the drop, under
        the tenant lock (mutually exclusive with :meth:`apply_upsert`, so
        a hibernation snapshot can never miss accepted rows); its return
        value becomes the tenant's ``page_plan``."""
        with self._lock:
            if self.tier == HOT:
                if snapshot_cb is not None and self.hot_obj is not None:
                    plan = snapshot_cb(self.hot_obj)
                    if plan is not None:
                        self.page_plan = plan
                freed = self.hot_bytes if self.hot_obj is not None else 0
                self.hot_obj = None
                to = WARM if self.warm_index is not None else COLD
                if to == COLD and self.warm_index is not None:
                    freed += self.warm_bytes
                    self.warm_index = None
            elif self.tier == WARM:
                freed = self.warm_bytes if self.warm_index is not None else 0
                self.warm_index = None
                to = COLD
            else:
                return None
            rec = {"tenant": self.name, "from": self.tier, "to": to,
                   "freed_bytes": int(freed)}
            self.tier = to
            self.demotions += 1
            self.last_demoted = now
        return rec

    @property
    def hot_path(self) -> str:
        return os.path.join(self.snapshot_dir, f"{self.name}.hot.raft")

    @property
    def warm_path(self) -> str:
        return os.path.join(self.snapshot_dir, f"{self.name}.warm.raft")

    @property
    def warm_ids_path(self) -> str:
        return os.path.join(self.snapshot_dir, f"{self.name}.warm_ids.raft")

    def resident_bytes(self) -> int:
        """Predicted bytes this tenant holds resident at its current tier
        (HOT keeps the warm codes too — the always-resident demotion
        fast path)."""
        with self._lock:
            total = 0
            if self.hot_obj is not None:
                total += self.hot_bytes
            if self.warm_index is not None:
                total += self.warm_bytes
            return total

    def slo_row(self) -> dict:
        """Per-tenant SLO row: serve counts by outcome + latency
        percentiles over the recent window (the per-tenant half of the
        acceptance's 'per-tenant SLO rows exported')."""
        with self._lock:
            row = {
                "served": int(self.serves),
                "degraded": int(self.degraded_serves),
                **{k: int(v) for k, v in sorted(self.outcomes.items())},
            }
            lats = (np.asarray(self.lats, dtype=np.float64)
                    if self.lats else None)
        if lats is not None:
            row["p50_ms"] = round(float(np.percentile(lats, 50)) * 1e3, 3)
            row["p99_ms"] = round(float(np.percentile(lats, 99)) * 1e3, 3)
        return row


def _family_of(index) -> str:
    """The costmodel family kind of a registered object (also validates
    that the capacity plane knows how to predict its residency)."""
    layout = costmodel.index_layout(index)
    return layout["kind"]


def _extract_rows(index) -> Tuple[np.ndarray, np.ndarray]:
    """(rows, ids) of the raw vectors an index still carries — the warm
    twin's training set. Families that keep no raw rows (ivf_pq codes)
    raise; their tenants tier HOT→COLD directly unless a ``warm_index``
    was supplied at registration."""
    from raft_tpu.neighbors import brute_force as bf_mod
    from raft_tpu.neighbors import cagra as cagra_mod
    from raft_tpu.neighbors import ivf_flat as flat_mod
    from raft_tpu.serving.store import PagedListStore

    if isinstance(index, PagedListStore):
        return _extract_rows(index.compact())
    if isinstance(index, flat_mod.IvfFlatIndex):
        data = np.asarray(index.list_data).reshape(-1, index.dim)
        ids = np.asarray(index.list_ids).reshape(-1)
        live = ids >= 0
        return data[live].astype(np.float32), ids[live].astype(np.int64)
    if isinstance(index, bf_mod.BruteForceIndex):
        data = np.asarray(index.dataset, dtype=np.float32)
        return data, np.arange(data.shape[0], dtype=np.int64)
    if isinstance(index, cagra_mod.CagraIndex):
        data = np.asarray(index.dataset, dtype=np.float32)
        return data, np.arange(data.shape[0], dtype=np.int64)
    raise TypeError(
        f"{type(index).__name__} carries no raw rows to derive a warm BQ "
        f"twin from — pass warm_index= at registration (or accept "
        f"HOT→COLD demotion)")


def _warm_twin(index, warm_params=None):
    """Build the tenant's warm tier: an IvfBqIndex over the index's own
    rows (sign codes at bits·rot_dim/8 bytes/row — the 32×-compression
    residency floor) plus the host-side position→source-id map its
    degraded results translate through."""
    from raft_tpu.neighbors import ivf_bq

    rows, ids = _extract_rows(index)
    n = int(rows.shape[0])
    if n < 1:
        raise ValueError("cannot build a warm twin over an empty index")
    if warm_params is None:
        metric = getattr(index, "metric", "sqeuclidean")
        if metric not in ivf_bq.SUPPORTED_METRICS:
            metric = "sqeuclidean"
        warm_params = ivf_bq.IvfBqParams(
            n_lists=max(1, min(32, n // 64)), metric=metric,
            kmeans_n_iters=5, list_size_cap=0)
    warm = ivf_bq.build(rows, warm_params)
    return warm, ids


def _merge_pending(queries, vals, ids, k, metric, rows_p, ids_p,
                   deletes) -> Tuple[np.ndarray, np.ndarray]:
    """Fold a tenant's buffered mutations into a warm-tier result: mask
    pending-deleted ids out, score the pending rows EXACTLY (they are
    fp32 in the buffer — no BQ quantization), and re-select top-k over
    the union. Keeps the degraded serve read-your-writes: a row upserted
    while the tenant is WARM is visible to the very next query."""
    bigger = metric == "inner_product"   # brute_force._MAX_METRICS shape
    worst = -np.inf if bigger else np.inf
    vals = np.where(ids < 0, worst, vals)   # pads must never win a merge
    if deletes:
        dead = np.isin(ids, np.fromiter(deletes, dtype=np.int64))
        vals = np.where(dead, worst, vals)
        ids = np.where(dead, -1, ids)
    if rows_p is not None:
        q = np.ascontiguousarray(queries, dtype=np.float32)
        ip = q @ rows_p.T
        if metric == "inner_product":
            scores = ip
        elif metric == "cosine":
            qn = np.linalg.norm(q, axis=1, keepdims=True)
            rn = np.linalg.norm(rows_p, axis=1)[None, :]
            scores = 1.0 - ip / np.maximum(qn * rn, 1e-30)
        else:
            d = np.maximum((q ** 2).sum(1, keepdims=True)
                           + (rows_p ** 2).sum(1)[None, :] - 2.0 * ip, 0.0)
            scores = np.sqrt(d) if metric == "euclidean" else d
        vals = np.concatenate([vals, scores.astype(vals.dtype)], axis=1)
        ids = np.concatenate(
            [ids, np.broadcast_to(ids_p, scores.shape).astype(ids.dtype)],
            axis=1)
    order = np.argsort(-vals if bigger else vals, axis=1,
                       kind="stable")[:, :k]
    return (np.take_along_axis(vals, order, axis=1),
            np.take_along_axis(ids, order, axis=1))


def _default_search_fn(kind: str) -> Callable:
    """Hot-tier dispatch for the families the plane serves natively."""
    def run(obj, queries, k, n_probes=20, **kw):
        from raft_tpu.neighbors import brute_force as bf_mod
        from raft_tpu.neighbors import ivf_bq, ivf_flat, ivf_pq

        if kind == "paged_store":
            from raft_tpu import serving

            return serving.search(obj, queries, k, n_probes=n_probes, **kw)
        if kind == "brute_force":
            return bf_mod.search(obj, queries, k, **kw)
        fam = {"ivf_flat": ivf_flat, "ivf_pq": ivf_pq, "ivf_bq": ivf_bq}[kind]
        return fam.search(obj, queries, k, n_probes=n_probes, **kw)

    return run


class TenantRegistry:
    """Thread-safe bookkeeping of the named tenants: tier state, the
    predicted residency ledger, and LRU ordering. Policy (admission,
    eviction sizing, promotion) lives in :class:`CapacityController`."""

    def __init__(self):
        self._lock = threading.RLock()
        self._tenants: Dict[str, Tenant] = {}

    def register(self, name: str, index, snapshot_dir,
                 warm_index=None, warm_ids=None, warm_params=None,
                 warm: bool = True,
                 search_fn: Optional[Callable] = None,
                 save_snapshots: bool = True) -> Tenant:
        """Create tenant ``name`` over ``index``: predicts its per-tier
        residency, builds the warm BQ twin (unless supplied or
        underivable), and writes the hot + warm v2 snapshots that
        demotion relies on (a tier drop must never lose the only copy).
        Registration is the expensive, off-serving-path moment — demote
        and promote only move already-prepared artifacts."""
        name = str(name)
        snapshot_dir = os.fspath(snapshot_dir)
        with self._lock:
            if name in self._tenants:
                raise ValueError(f"tenant {name!r} already registered")
        kind = _family_of(index)
        tenant = Tenant(name, kind, snapshot_dir)
        tenant.hot_obj = index
        tenant.hot_bytes = costmodel.predict_index_bytes(
            **costmodel.index_layout(index))
        tenant.search_fn = search_fn or _default_search_fn(kind)
        if warm_index is None and warm:
            try:
                warm_index, warm_ids = _warm_twin(index, warm_params)
            except TypeError:
                warm_index = None  # no raw rows: HOT→COLD tenant
        if warm_index is not None:
            tenant.warm_index = warm_index
            tenant.warm_enabled = True
            tenant.warm_ids = (np.asarray(warm_ids, dtype=np.int64)
                               if warm_ids is not None else None)
            tenant.warm_bytes = costmodel.predict_index_bytes(
                **costmodel.index_layout(warm_index))
        if save_snapshots:
            self._save_snapshots(tenant, index)
        tenant.touch()
        with self._lock:
            # re-check at insert: a concurrent same-name registration
            # must lose LOUDLY, not silently replace the winner's ledger
            if name in self._tenants:
                raise ValueError(f"tenant {name!r} already registered")
            self._tenants[name] = tenant
        if obs.enabled():
            obs.add("capacity.tenants.registered")
        return tenant

    def _save_snapshots(self, tenant: Tenant, index) -> None:
        from raft_tpu.core.serialize import save_arrays
        from raft_tpu.serving.store import PagedListStore

        os.makedirs(tenant.snapshot_dir, exist_ok=True)
        hot = index.compact() if isinstance(index, PagedListStore) else index
        hot.save(tenant.hot_path)
        if tenant.warm_index is not None:
            tenant.warm_index.save(tenant.warm_path)
            if tenant.warm_ids is not None:
                save_arrays(tenant.warm_ids_path,
                            {"kind": "capacity_warm_ids",
                             "tenant": tenant.name},
                            {"ids": tenant.warm_ids})

    def get(self, name: str) -> Tenant:
        with self._lock:
            try:
                return self._tenants[name]
            except KeyError:
                raise KeyError(f"unknown tenant {name!r} "
                               f"(have {sorted(self._tenants)})") from None

    def remove(self, name: str) -> None:
        with self._lock:
            self._tenants.pop(name, None)

    def names(self) -> list:
        with self._lock:
            return sorted(self._tenants)

    def tenants(self) -> list:
        with self._lock:
            return list(self._tenants.values())

    def touch(self, name: str) -> None:
        self.get(name).touch()

    def resident_bytes(self) -> int:
        """The budgeter's ledger: predicted resident bytes across every
        tenant at its current tier — the ``bytes_in_use`` the controller
        projects dispatches against (deterministic, synthetic-budget
        friendly: the plane accounts what it registered, not whatever
        else the process holds)."""
        with self._lock:
            return sum(t.resident_bytes() for t in self._tenants.values())

    def lru(self, exclude=()) -> list:
        """Demotion candidates, least-recently-served first (COLD tenants
        hold nothing to free and are skipped)."""
        exclude = set(exclude)
        with self._lock:
            cands = [t for t in self._tenants.values()
                     if t.name not in exclude and t.tier != COLD]
        return sorted(cands, key=lambda t: t.last_served)

    def tier_counts(self) -> dict:
        with self._lock:
            counts = {HOT: 0, WARM: 0, COLD: 0}
            for t in self._tenants.values():
                counts[t.tier] += 1
            return counts


# ---------------------------------------------------------------------------
# the acting controller
# ---------------------------------------------------------------------------


class CapacityController:
    """Binding admission + tiered residency over a :class:`TenantRegistry`.

    ``budget_bytes``: the HBM budget the registry is packed against
    (default: :func:`obs.costmodel.hbm_budget` — the
    ``RAFT_TPU_OBS_HBM_BYTES`` override or the device allocator limit;
    0/unknown admits everything, recorded). All admission projections use
    the registry's PREDICTED resident bytes as ``bytes_in_use``.
    """

    def __init__(self, registry: Optional[TenantRegistry] = None, *,
                 budget_bytes: Optional[int] = None,
                 max_demotions: Optional[int] = None,
                 window_s: Optional[float] = None,
                 promote_deadline_s: Optional[float] = None):
        self.registry = registry or TenantRegistry()
        if budget_bytes is not None:
            self.budget_bytes = int(budget_bytes)
            self.budget_source = "caller"
        else:
            budget = costmodel.hbm_budget()
            self.budget_bytes = int(budget["bytes"])
            self.budget_source = budget["source"]
        self.max_demotions = (int(max_demotions) if max_demotions is not None
                              else default_max_demotions())
        self.window_s = (float(window_s) if window_s is not None
                         else default_window_s())
        self.promote_deadline_s = (
            float(promote_deadline_s) if promote_deadline_s is not None
            else default_promote_deadline())
        self._lock = threading.RLock()
        self._demotion_times: deque = deque(maxlen=max(self.max_demotions, 1))
        self._promote_lats: deque = deque(maxlen=256)
        self._counts = {"demotions": 0, "promotions": 0, "rejections": 0,
                        "promote_failures": 0, "promote_denied": 0,
                        "queued_degraded": 0, "upserts": 0, "deletes": 0,
                        "buffered_upserts": 0, "replays": 0}

    # -- registration -------------------------------------------------------
    def register(self, name: str, index, snapshot_dir, **kw) -> Tenant:
        """Admission-placed registration: the tenant lands HOT when its
        full residency fits the budget (after an eviction attempt), WARM
        when only the codes fit, COLD otherwise — a registry growing past
        its budget degrades tier by tier instead of overcommitting."""
        with obs.record_span("capacity::register",
                             attrs={"tenant": str(name)}
                             if obs.enabled() else None):
            tenant = self.registry.register(name, index, snapshot_dir, **kw)
            # the tenant is ALREADY in the ledger — project the ledger as
            # it stands (predicted delta 0), not its bytes a second time
            rec = self._admission(0, entry="capacity.register")
            if rec["verdict"] == costmodel.REJECT:
                self.make_room(rec.get("shortfall_bytes", 0),
                               exclude=(tenant.name,))
                rec = self._admission(0, entry="capacity.register")
            if rec["verdict"] != costmodel.ADMIT:
                self._demote_one(tenant)          # HOT -> WARM (or COLD)
                if tenant.tier == WARM and self._admission(
                        0, entry="capacity.register")["verdict"] \
                        != costmodel.ADMIT:
                    self._demote_one(tenant)      # WARM -> COLD
            return tenant

    # -- admission ----------------------------------------------------------
    def _admission(self, predicted, entry: str) -> dict:
        return costmodel.check_admission(
            predicted, entry=entry,
            budget_bytes=self.budget_bytes or None,
            bytes_in_use=self.registry.resident_bytes())

    def admit(self, predicted, entry: str = "", tenant: str = "") -> dict:
        """The BINDING verdict for one predicted footprint: checks
        admission against the budgeter's ledger; a REJECT first sizes an
        eviction from the verdict's ``shortfall_bytes``, demotes
        least-recently-served tenants (never the requesting one), and
        re-checks. The returned record's verdict is final — the caller
        dispatches (admit), holds/degrades (queue) or rejects classified
        (reject)."""
        with obs.record_span("capacity::admit",
                             attrs={"entry": entry} if obs.enabled()
                             else None):
            with self._lock:
                rec = self._admission(predicted, entry)
                if rec["verdict"] == costmodel.REJECT:
                    demoted = self.make_room(
                        rec.get("shortfall_bytes") or rec["predicted_bytes"],
                        exclude=(tenant,) if tenant else ())
                    if demoted:
                        rec = self._admission(predicted, entry)
                        rec["demoted"] = [d["tenant"] for d in demoted]
            if tenant:
                try:
                    self.registry.get(tenant).record_verdict(rec["verdict"])
                except KeyError:
                    pass
            if obs.enabled():
                obs.add(f"capacity.verdict.{rec['verdict']}")
            return rec

    def cost_model_for(self, name: str, k: int, n_probes: int) -> Callable:
        """``batch_size -> estimate dict`` over tenant ``name``'s CURRENT
        hot/warm object — the ``QueryQueue(cost_model=...)`` hook for a
        capacity-managed queue (pair it with ``capacity=controller`` to
        make the verdicts binding)."""

        def cost(batch: int) -> dict:
            tenant = self.registry.get(name)
            obj = tenant.hot_obj if tenant.hot_obj is not None \
                else tenant.warm_index
            if obj is None:
                return {"transient_bytes": 0, "total_bytes": 0}
            return costmodel.estimate_search(obj, q=int(batch), k=k,
                                             n_probes=n_probes)

        return cost

    # -- mutation (any tier) -------------------------------------------------
    def upsert(self, name: str, vectors, ids=None) -> dict:
        """Upsert rows into tenant ``name`` at WHATEVER tier it occupies:
        HOT applies to the live paged store; WARM/COLD buffers for replay
        at promote (explicit ids required) while the warm tier serves the
        buffered rows exactly. A HOT apply re-predicts the ledger — live
        growth changes every later admission projection."""
        tenant = self.registry.get(name)
        attrs = {"tenant": name, "tier": tenant.tier} \
            if obs.enabled() else None
        with obs.record_span("capacity::upsert", attrs=attrs):
            rec = tenant.apply_upsert(vectors, ids)
            if rec["applied"] and tenant.hot_obj is not None:
                with tenant._lock:
                    tenant.hot_bytes = costmodel.predict_index_bytes(
                        **costmodel.index_layout(tenant.hot_obj))
            with self._lock:
                self._counts["upserts"] += 1
                if rec["buffered"]:
                    self._counts["buffered_upserts"] += 1
            if obs.enabled():
                obs.add("capacity.upserts")
                if rec["buffered"]:
                    obs.add("capacity.upserts.buffered")
            if rec["buffered"]:
                record_event("capacity_upsert_buffered", tenant=name,
                             tier=rec["tier"], rows=rec["buffered"])
            return rec

    def delete(self, name: str, ids) -> dict:
        """Delete ids from tenant ``name`` at any tier (the buffered half
        mirrors :meth:`upsert`)."""
        tenant = self.registry.get(name)
        attrs = {"tenant": name, "tier": tenant.tier} \
            if obs.enabled() else None
        with obs.record_span("capacity::delete", attrs=attrs):
            rec = tenant.apply_delete(ids)
            with self._lock:
                self._counts["deletes"] += 1
            if obs.enabled():
                obs.add("capacity.deletes")
            return rec

    # -- eviction (tier-down) -----------------------------------------------
    def _window_demotions(self, now: float) -> int:
        return sum(1 for t in self._demotion_times
                   if now - t <= self.window_s)

    def _hibernate_paged(self, tenant: Tenant) -> Optional[Callable]:
        """The HOT→WARM snapshot callback for a paged (mutable) tenant:
        compact the live store, overwrite the hot snapshot with its
        CURRENT rows (the registration-time snapshot is stale the moment
        the first upsert lands), and capture the page plan —
        ``restore_shape`` on promote re-creates the same compiled-shape
        envelope so the round trip costs zero growth retraces. Non-paged
        tenants return None: their registration snapshot is still exact."""
        if tenant.kind != "paged_store":
            return None

        def snap(hot_obj) -> Optional[dict]:
            from raft_tpu.serving.store import PagedListStore

            if not isinstance(hot_obj, PagedListStore):
                return None
            packed = hot_obj.compact()
            packed.save(tenant.hot_path)
            if obs.enabled():
                obs.add("capacity.hibernates")
            record_event("capacity_hibernate", tenant=tenant.name,
                         rows=int(hot_obj.size))
            return {"kind": _family_of(packed),
                    "page_rows": int(hot_obj.page_rows),
                    "capacity_pages": int(hot_obj.capacity_pages),
                    "table_width": int(hot_obj.table_width)}

        return snap

    def _demote_one(self, tenant: Tenant) -> Optional[dict]:
        """One tier down; returns the demotion record (None when the
        tenant already holds nothing). HOT drops the full index (the warm
        codes stay resident — the instant path); WARM drops the codes. A
        paged tenant hibernates first (fresh snapshot + page plan); a
        FAILED hibernation aborts the demotion classified — dropping the
        only copy of accepted mutations is never an eviction option."""
        now = time.monotonic()
        try:
            rec = tenant.demote_one_tier(
                now, snapshot_cb=self._hibernate_paged(tenant))
        except Exception as e:
            kind = resilience.classify(e)
            if obs.enabled():
                obs.add("capacity.demote.failed")
                obs.add(f"capacity.demote.failed.{kind}")
            record_event("capacity_demote_failed", tenant=tenant.name,
                         kind=kind, error=repr(e)[:200])
            return None
        if rec is None:
            return None
        with self._lock:
            self._counts["demotions"] += 1
            self._demotion_times.append(now)
        if obs.enabled():
            obs.add("capacity.demotions")
            obs.add(f"capacity.tenant.{tenant.name}.demotions")
        record_event("capacity_demote", **rec)
        return rec

    def demote(self, name: str) -> Optional[dict]:
        """Demote tenant ``name`` one tier (public entry; eviction sizing
        goes through :meth:`make_room`)."""
        with obs.record_span("capacity::demote",
                             attrs={"tenant": name} if obs.enabled()
                             else None):
            return self._demote_one(self.registry.get(name))

    def make_room(self, shortfall_bytes: int, exclude=()) -> list:
        """Free at least ``shortfall_bytes`` predicted bytes by demoting
        least-recently-served tenants tier-down. Bounded by the
        per-window demotion budget (anti-livelock): when the window is
        exhausted the eviction stops short, classified — the caller's
        re-check then rejects rather than thrashing the registry."""
        shortfall = int(shortfall_bytes)
        if shortfall <= 0:
            return []
        demoted = []
        freed = 0
        with self._lock:
            # multi-pass: one tier step per tenant per pass (spreads the
            # pain — WARM everywhere before COLD anywhere), repeated
            # until the shortfall is covered, the window budget runs out,
            # or nothing is left to free
            while freed < shortfall:
                now = time.monotonic()
                progressed = False
                for tenant in self.registry.lru(exclude=exclude):
                    if freed >= shortfall:
                        break
                    if self._window_demotions(now) >= self.max_demotions:
                        record_event("capacity_demotion_limited",
                                     shortfall_bytes=shortfall - freed,
                                     window_s=self.window_s,
                                     max_demotions=self.max_demotions)
                        if obs.enabled():
                            obs.add("capacity.demotions.limited")
                        return demoted
                    rec = self._demote_one(tenant)
                    if rec is not None:
                        demoted.append(rec)
                        freed += rec["freed_bytes"]
                        progressed = True
                if not progressed:
                    break
        return demoted

    # -- promotion (tier-up) -------------------------------------------------
    def _load_hot(self, tenant: Tenant):
        """Reload the packed hot index from the tenant's v2 snapshot (the
        serialize.load.read faultpoint inside load_arrays covers the
        read)."""
        from raft_tpu.neighbors import brute_force as bf_mod
        from raft_tpu.neighbors import cagra as cagra_mod
        from raft_tpu.neighbors import ivf_bq, ivf_flat, ivf_pq

        cls = {"ivf_flat": ivf_flat.IvfFlatIndex,
               "ivf_pq": ivf_pq.IvfPqIndex,
               "ivf_bq": ivf_bq.IvfBqIndex,
               "brute_force": bf_mod.BruteForceIndex,
               "cagra": cagra_mod.CagraIndex}.get(tenant.kind)
        if cls is None:
            # a paged store compacts to ivf_flat/pq/bq for its snapshot;
            # a paged TENANT rehydrates back to a PagedListStore on the
            # hibernation page plan — mutability survives the tier cycle
            from raft_tpu.core.serialize import load_arrays

            meta, _ = load_arrays(tenant.hot_path)
            kind = meta.get("kind")
            cls = {"ivf_flat": ivf_flat.IvfFlatIndex,
                   "ivf_pq": ivf_pq.IvfPqIndex,
                   "ivf_bq": ivf_bq.IvfBqIndex}[kind]
            packed = cls.load(tenant.hot_path)
            if tenant.kind == "paged_store":
                from raft_tpu.serving.store import PagedListStore

                plan = tenant.page_plan or {}
                store = PagedListStore.from_index(
                    packed, page_rows=plan.get("page_rows"))
                store.restore_shape(plan.get("capacity_pages", 0),
                                    plan.get("table_width", 0))
                return store
            tenant.set_search_fn(_default_search_fn(kind))
            return packed
        return cls.load(tenant.hot_path)

    def _load_warm(self, tenant: Tenant) -> None:
        """Page the warm codes back in from the warm snapshot (COLD →
        WARM): the small, admission-checked read that lets a cold tenant
        serve degraded while the full promote happens off the hot path."""
        from raft_tpu.core.serialize import load_arrays
        from raft_tpu.neighbors import ivf_bq

        if not os.path.exists(tenant.warm_path):
            raise FileNotFoundError(
                f"tenant {tenant.name!r} has no warm snapshot at "
                f"{tenant.warm_path} — it cannot serve degraded; promote "
                f"it instead")
        warm = ivf_bq.IvfBqIndex.load(tenant.warm_path)
        ids = None
        if os.path.exists(tenant.warm_ids_path):
            _, arrays = load_arrays(tenant.warm_ids_path)
            ids = np.asarray(arrays["ids"], dtype=np.int64)
        tenant.adopt_warm(warm, ids, costmodel.predict_index_bytes(
            **costmodel.index_layout(warm)))

    def promote(self, name: str) -> dict:
        """Restore tenant ``name``'s snapshot to full HOT residency with
        MEASURED hot-swap latency. Admission-gated (only an ADMIT
        promotes — the budgeter invariant survives the reverse path) and
        deadline-bounded through the faultpointed
        ``serving.capacity.promote`` site: an injected/real oom or hang
        lands classified and the tenant stays in its prior tier. Returns
        the classified record, never raises for classified failures."""
        tenant = self.registry.get(name)
        attrs = {"tenant": name, "tier": tenant.tier} \
            if obs.enabled() else None
        with obs.record_span("capacity::promote", attrs=attrs):
            if tenant.tier == HOT:
                return {"status": "noop", "tenant": name, "tier": HOT}
            delta = tenant.hot_bytes
            if tenant.warm_index is None and tenant.warm_enabled:
                delta += tenant.warm_bytes
            rec = self.admit(delta, entry="capacity.promote", tenant=name)
            if rec["verdict"] != costmodel.ADMIT:
                with self._lock:
                    self._counts["promote_denied"] += 1
                if obs.enabled():
                    obs.add("capacity.promote.denied")
                return {"status": "denied", "tenant": name,
                        "tier": tenant.tier, "verdict": rec["verdict"]}
            prior = tenant.tier
            t0 = time.perf_counter()
            try:
                with resilience.Deadline(self.promote_deadline_s,
                                         label="capacity.promote"):
                    resilience.faultpoint("serving.capacity.promote")
                    hot = self._load_hot(tenant)
                    if tenant.warm_index is None and tenant.warm_enabled:
                        self._load_warm(tenant)
            except Exception as e:
                kind = resilience.classify(e)
                with self._lock:
                    self._counts["promote_failures"] += 1
                if obs.enabled():
                    obs.add("capacity.promote.failed")
                    obs.add(f"capacity.promote.failed.{kind}")
                record_event("capacity_promote_failed", tenant=name,
                             kind=kind, error=repr(e)[:200])
                return {"status": "error", "tenant": name, "tier": prior,
                        "kind": kind, "error": repr(e)[:200]}
            dt = time.perf_counter() - t0
            # re-predict: the restored object can differ from what was
            # registered (a paged-store tenant promotes to its COMPACTED
            # packed snapshot) — a stale ledger entry would mis-project
            # every later admission
            tenant.adopt_hot(hot, costmodel.predict_index_bytes(
                **costmodel.index_layout(hot)))
            # mutations accepted while demoted replay into the restored
            # store AFTER the tier flip: once the tenant is HOT no new
            # batch can buffer, so one drain here catches everything
            replay = self._replay_pending(tenant)
            with self._lock:
                self._counts["promotions"] += 1
                self._promote_lats.append(dt)
            if obs.enabled():
                obs.add("capacity.promotions")
                obs.add(f"capacity.tenant.{name}.promotions")
                obs.observe("capacity.promote_s", dt)
            record_event("capacity_promote", tenant=name,
                         promote_s=round(dt, 6))
            return {"status": "ok", "tenant": name, "tier": HOT,
                    "promote_s": dt, "from": prior,
                    "replayed_rows": replay["rows"],
                    "replayed_deletes": replay["deletes"]}

    def _replay_pending(self, tenant: Tenant) -> dict:
        """Apply the drained WARM/COLD mutation buffer to the freshly
        promoted store: upsert batches in arrival order, then the
        tombstones (:meth:`Tenant.drain_pending` documents why that
        ordering is exact). The ledger re-predicts afterwards — replayed
        rows change the resident footprint."""
        batches, deletes = tenant.drain_pending()
        if not batches and not deletes:
            return {"rows": 0, "deletes": 0}
        store = tenant.hot_obj
        rows_n = 0
        try:
            for rows, ids_np in batches:
                store.upsert(rows, ids_np)
                rows_n += int(rows.shape[0])
            if deletes:
                store.delete(np.asarray(deletes, dtype=np.int64))
        except Exception as e:
            kind = resilience.classify(e)
            if obs.enabled():
                obs.add(f"capacity.replay.failed.{kind}")
            record_event("capacity_replay_failed", tenant=tenant.name,
                         kind=kind, error=repr(e)[:200])
            return {"rows": rows_n, "deletes": 0}
        with tenant._lock:
            tenant.hot_bytes = costmodel.predict_index_bytes(
                **costmodel.index_layout(store))
        with self._lock:
            self._counts["replays"] += 1
        if obs.enabled():
            obs.add("capacity.replays")
        record_event("capacity_replay", tenant=tenant.name, rows=rows_n,
                     deletes=len(deletes))
        return {"rows": rows_n, "deletes": len(deletes)}

    def autopromote(self, max_promotions: int = 1) -> list:
        """Opportunistic tier-up of the most-recently-served non-HOT
        tenants whose full residency ADMITs — the reverse path the chaos
        bench drives between request windows (off the hot path). Tenants
        demoted within the current window are skipped (anti-thrash)."""
        promoted = []
        now = time.monotonic()
        cands = sorted(
            (t for t in self.registry.tenants()
             if t.tier != HOT and t.serves > 0
             and now - t.last_demoted > self.window_s),
            key=lambda t: t.last_served, reverse=True)
        for tenant in cands:
            if len(promoted) >= max_promotions:
                break
            rec = self.promote(tenant.name)
            if rec.get("status") == "ok":
                promoted.append(rec)
        return promoted

    # -- serving -------------------------------------------------------------
    def _serve_warm(self, tenant: Tenant, queries, k: int,
                    n_probes: int) -> TenantResult:
        from raft_tpu.neighbors import ivf_bq

        warm = tenant.warm_index
        np_warm = max(1, min(int(n_probes), warm.n_lists))
        kw = min(int(k), min(np_warm * warm.max_list_size, 512))
        vals, ids = ivf_bq.search(warm, queries, kw, n_probes=np_warm)
        vals = np.asarray(vals)
        ids = np.asarray(ids)
        if tenant.warm_ids is not None:
            live = ids >= 0
            out_ids = np.full(ids.shape, -1, dtype=np.int64)
            out_ids[live] = tenant.warm_ids[ids[live]]
            ids = out_ids
        if kw < k:  # pad to the caller's k so batch shapes line up
            pad = int(k) - kw
            vals = np.concatenate(
                [vals, np.full((vals.shape[0], pad), np.inf,
                               dtype=vals.dtype)], axis=1)
            ids = np.concatenate(
                [ids, np.full((ids.shape[0], pad), -1, dtype=ids.dtype)],
                axis=1)
        pend = tenant.pending_view()
        if pend is not None:
            vals, ids = _merge_pending(np.asarray(queries, np.float32),
                                       vals, ids, int(k), warm.metric,
                                       *pend)
        tenant.record_degraded()
        if obs.enabled():
            obs.add("capacity.serves.degraded")
            obs.add(f"capacity.tenant.{tenant.name}.degraded")
        # the SERVING tier: a HOT tenant queued into its warm codes still
        # served from WARM — the result says what actually answered
        return TenantResult(vals, ids, tenant.name, WARM, degraded=True)

    def _hold_for_admit(self, predicted, entry: str, tenant: str) -> dict:
        """QUEUE with no warm fallback: hold under the caller's active
        Deadline, re-checking admission — expiry raises the classified
        DEADLINE (never a hang); with no deadline the hold is a bounded
        number of re-checks before the verdict goes final."""
        for _ in range(64):
            dl = resilience.active_deadline()
            if dl is None:
                break
            resilience.check_deadline()   # raises classified on expiry
            time.sleep(min(0.005, max(dl.remaining(), 0.0) or 0.001))
            rec = self.admit(predicted, entry=entry, tenant=tenant)
            if rec["verdict"] != costmodel.QUEUE:
                return rec
        resilience.check_deadline()
        return self.admit(predicted, entry=entry, tenant=tenant)

    def search(self, name: str, queries, k: int, n_probes: int = 20,
               **kw) -> TenantResult:
        """Serve one query batch against tenant ``name`` under the
        binding admission policy. HOT + ADMIT serves exact; QUEUE
        pressure (or a WARM/COLD tier) serves DEGRADED from the
        always-resident BQ codes with ``degraded=True`` stamped; a final
        REJECT raises :class:`CapacityRejected`. A COLD tenant first
        pages its warm codes back in (admission-checked)."""
        tenant = self.registry.get(name)
        self.registry.touch(name)
        t0 = time.monotonic()
        attrs = None
        if obs.enabled():
            attrs = {"tenant": name, "tier": tenant.tier}
            obs.add(f"capacity.tenant.{name}.serves")
        with obs.record_span("capacity::search", attrs=attrs):
            try:
                result = self._search_impl(tenant, queries, k, n_probes,
                                           **kw)
            except Exception as e:
                kind = resilience.classify(e)
                outcome = REJECTED if isinstance(e, CapacityRejected) \
                    else kind
                tenant.record_outcome(outcome)
                if outcome == REJECTED:
                    with self._lock:
                        self._counts["rejections"] += 1
                    if obs.enabled():
                        obs.add("capacity.rejections")
                record_event("capacity_serve_failed", tenant=name,
                             kind=kind, outcome=outcome,
                             error=repr(e)[:200])
                raise
            dt = time.monotonic() - t0
            tenant.record_serve(dt)
            if obs.enabled():
                obs.observe("capacity.serve_latency_s", dt)
                if result.degraded:
                    # the attribute the shadow/SLO plane keys the recall
                    # hit off: degraded serves are a separate series
                    obs.observe("capacity.degraded_latency_s", dt)
            return result

    def _search_impl(self, tenant: Tenant, queries, k, n_probes,
                     **kw) -> TenantResult:
        if tenant.tier == COLD and not tenant.warm_enabled:
            raise CapacityRejected(
                f"tenant {tenant.name!r} is COLD and has no warm tier — "
                f"promote it")
        if tenant.tier == COLD:
            # page the codes back in (small; admission-checked with
            # eviction allowed) — failure leaves the tenant COLD
            rec = self.admit(tenant.warm_bytes, entry="capacity.warm_load",
                             tenant=tenant.name)
            if rec["verdict"] == costmodel.REJECT:
                raise CapacityRejected(
                    f"tenant {tenant.name!r} is COLD and its warm codes "
                    f"({tenant.warm_bytes} B) do not fit the budget "
                    f"(projected {rec['projected_bytes']} of "
                    f"{rec['budget_bytes']} B)")
            self._load_warm(tenant)
        if tenant.tier == HOT and tenant.hot_obj is not None:
            q = int(np.asarray(queries).shape[0])
            try:
                est = costmodel.estimate_search(
                    tenant.hot_obj, q=q, k=int(k), n_probes=int(n_probes))
            except Exception as e:
                # an unpredictable family must not cost the dispatch:
                # admit with a zero estimate, classified
                record_event("capacity_estimate_error", tenant=tenant.name,
                             kind=resilience.classify(e),
                             error=repr(e)[:200])
                est = 0
            rec = self.admit(est, entry="capacity.search",
                             tenant=tenant.name)
            if rec["verdict"] != costmodel.ADMIT:
                # memory pressure on the exact dispatch: the graceful
                # path is the always-resident warm codes — a degraded
                # answer (stamped) instead of a refusal; eviction for a
                # REJECT already ran inside admit()
                if tenant.warm_index is not None:
                    with self._lock:
                        self._counts["queued_degraded"] += 1
                    if obs.enabled():
                        obs.add("capacity.queued_degraded")
                    return self._serve_warm(tenant, queries, k, n_probes)
                if rec["verdict"] == costmodel.QUEUE:
                    rec = self._hold_for_admit(est, "capacity.search",
                                               tenant.name)
            if rec["verdict"] == costmodel.REJECT:
                raise CapacityRejected(
                    f"dispatch for tenant {tenant.name!r} rejected: "
                    f"projected {rec['projected_bytes']} of "
                    f"{rec['budget_bytes']} B even after eviction")
            vals, ids = tenant.search_fn(tenant.hot_obj, queries, int(k),
                                         n_probes=int(n_probes), **kw)
            return TenantResult(vals, ids, tenant.name, HOT,
                                degraded=False)
        if tenant.warm_index is None:
            raise CapacityRejected(
                f"tenant {tenant.name!r} holds nothing resident at tier "
                f"{tenant.tier!r} and has no warm codes — promote it")
        return self._serve_warm(tenant, queries, k, n_probes)

    # -- reporting -----------------------------------------------------------
    def promote_latency(self) -> dict:
        with self._lock:
            lats = np.asarray(self._promote_lats, dtype=np.float64)
        out = {"count": int(lats.size)}
        if lats.size:
            out["p50_s"] = round(float(np.percentile(lats, 50)), 6)
            out["p99_s"] = round(float(np.percentile(lats, 99)), 6)
            out["max_s"] = round(float(lats.max()), 6)
        return out

    def report(self) -> dict:
        """The per-tenant capacity section ``obs.report.collect``
        embeds: budget + predicted residency, tier census, demotion/
        promotion/rejection counts, measured promote latency, and one
        SLO row per tenant (verdicts, outcomes, latency percentiles)."""
        resident = self.registry.resident_bytes()
        tiers = self.registry.tier_counts()
        with self._lock:
            counts = dict(self._counts)
        rows = {}
        for t in self.registry.tenants():
            rows[t.name] = {
                "tier": t.tier,
                "resident_bytes": int(t.resident_bytes()),
                "hot_bytes": int(t.hot_bytes),
                "warm_bytes": int(t.warm_bytes),
                "demotions": int(t.demotions),
                "promotions": int(t.promotions),
                "pending_rows": int(t.pending_rows),
                "verdicts": {k: int(v)
                             for k, v in sorted(t.verdicts.items())},
                "slo": t.slo_row(),
            }
        out = {
            "budget_bytes": int(self.budget_bytes),
            "budget_source": self.budget_source,
            "resident_bytes": int(resident),
            "resident_fraction": (round(resident / self.budget_bytes, 4)
                                  if self.budget_bytes else None),
            "tenants_resident_hot": tiers[HOT],
            "tenants_resident_warm": tiers[WARM],
            "tenants_cold": tiers[COLD],
            "promote": self.promote_latency(),
            **counts,
            "tenants": rows,
        }
        return out
