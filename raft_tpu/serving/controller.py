"""Online SLO burn-rate controller: the closed loop's serving half.

The offline tuner (raft_tpu/tuning/autotune.py) picks an operating point
on the Pareto frontier; this module keeps live serving AT it when traffic
misbehaves. A :class:`BurnRateController` is a deadline-bounded,
faultpointed background loop (the ``CompactionManager`` /
``MaintenanceManager`` pattern) that reads the :class:`SloEngine`'s
dual-window burns each tick and nudges **one knob per tick** through its
ordered :class:`KnobActuator` list:

* **hot** (a latency/availability SLO burning — fast window over
  threshold): step the first steppable actuator DOWN one rung —
  ``n_probes`` down, batch cap down, tier demote — cheapest latency
  relief first;
* **recall burning**: any recall-costing actuator sitting BELOW its
  tuned rung steps back UP immediately — latency relief is never bought
  by holding the recall SLO under water;
* **cool** for ``RAFT_TPU_TUNE_COOL_WINDOWS`` consecutive ticks: one
  nudged actuator reverts one rung toward the tuned point (hysteresis —
  a controller that re-raises on the first quiet tick livelocks).

The shadow-recall Wilson CI is a HARD guardrail: an actuator marked
``costs_recall`` is never stepped down while the sampler's ``ci_low``
sits at/under the recall floor — the controller acts on the batch cap
instead, or holds (counted ``guardrail_holds``). Every knob move lands
as a classified ``tuning.action`` event on the resilience ring — the
flight recorder folds it into the window timeline, so a tuning episode
is reconstructible from the recording alone. Per-tick action count is
bounded by ``RAFT_TPU_TUNE_MAX_ACTIONS`` (the capacity plane's
anti-livelock pattern).

Each tick is bounded by the tuner's window deadline knob
(``RAFT_TPU_TUNE_DEADLINE_S``) and faultpointed
(``serving.controller.tick`` — the round-7 standing gate; tier-1 arms
oom/hang/fatal): a faulted tick is skipped classified and serving never
wedges. Telemetry-off contract: a disabled registry means the controller
holds ZERO state and ``tick()``/``report()`` return None.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from raft_tpu import obs, resilience
from raft_tpu.resilience.retry import record_event

__all__ = [
    "COOL_WINDOWS_ENV",
    "CONTROL_INTERVAL_ENV",
    "MAX_ACTIONS_ENV",
    "BurnRateController",
    "KnobActuator",
    "default_control_interval",
    "default_cool_windows",
    "default_max_actions",
]

MAX_ACTIONS_ENV = "RAFT_TPU_TUNE_MAX_ACTIONS"
COOL_WINDOWS_ENV = "RAFT_TPU_TUNE_COOL_WINDOWS"
CONTROL_INTERVAL_ENV = "RAFT_TPU_TUNE_INTERVAL_S"

_DEFAULT_MAX_ACTIONS = 1
_DEFAULT_COOL_WINDOWS = 2
_DEFAULT_INTERVAL_S = 1.0

#: SLO kinds whose burn means "spend recall/throughput to buy latency"
_HOT_KINDS = ("latency", "availability")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    return int(raw) if raw.isdigit() and int(raw) > 0 else default


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        v = float(raw) if raw else default
    except ValueError:
        return default
    return v if v > 0 else default


def default_max_actions() -> int:
    """Knob moves the controller may take per tick
    (``RAFT_TPU_TUNE_MAX_ACTIONS``, default 1 — one knob per window)."""
    return _env_int(MAX_ACTIONS_ENV, _DEFAULT_MAX_ACTIONS)


def default_cool_windows() -> int:
    """Consecutive cool ticks before one revert toward the tuned point
    (``RAFT_TPU_TUNE_COOL_WINDOWS``, default 2)."""
    return _env_int(COOL_WINDOWS_ENV, _DEFAULT_COOL_WINDOWS)


def default_control_interval() -> float:
    """Background worker tick interval in seconds
    (``RAFT_TPU_TUNE_INTERVAL_S``, default 1.0)."""
    return _env_float(CONTROL_INTERVAL_ENV, _DEFAULT_INTERVAL_S)


class KnobActuator:
    """One live-settable serving knob: an ordered ladder (ascending
    latency cost — "down" buys latency), a getter and a setter reaching
    into the serving object (queue batch cap, searcher closure nprobe,
    capacity tier). The rung held at construction is the TUNED point the
    controller reverts toward. ``costs_recall`` marks the knobs the
    Wilson-CI guardrail protects."""

    def __init__(self, name: str, values, get, set, *,
                 costs_recall: bool = False):
        self.name = str(name)
        self.values = list(values)
        if not self.values:
            raise ValueError(f"actuator {name!r} has an empty ladder")
        self._get = get
        self._set = set
        self.costs_recall = bool(costs_recall)
        cur = get()
        if cur not in self.values:
            raise ValueError(
                f"actuator {name!r} live value {cur!r} not on its ladder")
        self.tuned_idx = self.values.index(cur)

    @property
    def idx(self) -> int:
        cur = self._get()
        return self.values.index(cur) if cur in self.values else \
            self.tuned_idx

    @property
    def value(self):
        return self._get()

    def step(self, direction: int):
        """Move one rung (clamped); returns (frm, to) after applying to
        the live object."""
        i = self.idx
        j = max(0, min(len(self.values) - 1, i + int(direction)))
        frm, to = self.values[i], self.values[j]
        if j != i:
            self._set(to)
        return frm, to


class BurnRateController:
    """Burn-rate-driven knob controller for one serving setup.

    ``engine`` is the :class:`raft_tpu.obs.slo.SloEngine` whose
    ``evaluate()`` drives the loop; ``actuators`` is the relief-priority
    list of :class:`KnobActuator` (first = cheapest latency relief);
    ``sampler`` (optional) is the shadow sampler whose Wilson ``ci_low``
    gates recall-costing moves against ``recall_floor`` (default: the
    engine's recall SLO target when one exists). Drive it
    deterministically (:meth:`pump` in the serving loop's idle gaps —
    what the bench and tier-1 do) or with :meth:`start`/:meth:`stop`.
    """

    def __init__(self, engine, actuators, *, sampler=None,
                 recall_floor: Optional[float] = None,
                 max_actions: Optional[int] = None,
                 cool_windows: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 interval_s: Optional[float] = None):
        self._enabled = obs.enabled()
        if not self._enabled:
            return  # telemetry off ⇒ ZERO controller state (NOOP contract)
        from raft_tpu.tuning.autotune import default_tune_deadline

        self.engine = engine
        self.actuators = list(actuators)
        if not self.actuators:
            raise ValueError("BurnRateController needs at least one "
                             "actuator")
        self.sampler = sampler
        self.recall_floor = (float(recall_floor)
                             if recall_floor is not None
                             else self._engine_recall_floor())
        self.max_actions = int(max_actions if max_actions is not None
                               else default_max_actions())
        self.cool_windows = int(cool_windows if cool_windows is not None
                                else default_cool_windows())
        self.deadline_s = float(deadline_s if deadline_s is not None
                                else default_tune_deadline())
        self.interval_s = float(interval_s if interval_s is not None
                                else default_control_interval())
        # counter plane: mutated by whichever thread wins _busy, read by
        # report() from serving threads — its own leaf lock, never held
        # across engine/sampler/actuator calls
        self._stats_lock = threading.Lock()
        self.ticks = 0            # guarded-by: _stats_lock, reads-ok
        self.nudges = 0           # guarded-by: _stats_lock, reads-ok
        self.reverts = 0          # guarded-by: _stats_lock, reads-ok
        self.holds = 0            # guarded-by: _stats_lock, reads-ok
        self.guardrail_holds = 0  # guarded-by: _stats_lock, reads-ok
        self.failures = 0         # guarded-by: _stats_lock, reads-ok
        self.breach_ticks = 0     # guarded-by: _stats_lock, reads-ok
        self.last_status: Optional[str] = None  # guarded-by: _stats_lock, reads-ok
        self._cool_streak = 0     # guarded-by: _stats_lock, reads-ok
        self._busy = threading.Lock()
        self._worker: Optional[threading.Thread] = None
        self._stopping = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    def _engine_recall_floor(self) -> Optional[float]:
        for slo in getattr(self.engine, "slos", ()) or ():
            if getattr(slo, "kind", None) == "recall":
                return float(slo.target)
        return None

    # -- one tick -----------------------------------------------------------
    def pump(self) -> Optional[dict]:
        """One control step if no other tick is in flight — the
        deterministic driver for serving loops and tier-1 tests. Returns
        the tick's decision dict, None when disabled or busy."""
        if not self._enabled:
            return None
        if not self._busy.acquire(blocking=False):
            return None  # another thread's tick is in flight
        try:
            return self._tick()
        finally:
            self._busy.release()

    def tick(self) -> Optional[dict]:
        """Alias for :meth:`pump` — the controller's unit of progress."""
        return self.pump()

    def _tick(self) -> dict:
        t0 = time.perf_counter()
        try:
            with obs.record_span("serving::controller_tick"):
                with resilience.Deadline(self.deadline_s,
                                         label="serving.controller"):
                    # faultpoint INSIDE the deadline scope: an armed hang
                    # spins on check_interrupt and is bounded by the tick
                    # deadline — a wedged tick must never wedge serving
                    resilience.faultpoint("serving.controller.tick")
                    decision = self._decide()
        except Exception as e:
            kind = resilience.classify(e)
            with self._stats_lock:
                self.ticks += 1
                self.failures += 1
                self.last_status = kind
            obs.add(f"tuning.tick.{kind.lower()}")
            record_event("tuning.tick_error", kind=kind,
                         error=repr(e)[:200])
            return {"status": kind, "actions": []}
        with self._stats_lock:
            self.ticks += 1
            self.last_status = decision["status"]
        if obs.enabled():
            obs.observe("tuning.tick_duration_s",
                        time.perf_counter() - t0)
        return decision

    def _decide(self) -> dict:
        rows = self.engine.evaluate() or {}
        hot = [n for n, r in rows.items() if isinstance(r, dict)
               and r.get("kind") in _HOT_KINDS
               and r.get("state") in ("warn", "breach")]
        recall_burn = [n for n, r in rows.items() if isinstance(r, dict)
                       and r.get("kind") == "recall"
                       and r.get("state") in ("warn", "breach")]
        breach = any(r.get("state") == "breach" for r in rows.values()
                     if isinstance(r, dict))
        actions: list = []
        budget = self.max_actions
        if recall_burn and budget > 0:
            act = self._revert_recall(recall_burn[0])
            if act is not None:
                actions.append(act)
                budget -= 1
        if hot:
            with self._stats_lock:
                self._cool_streak = 0
                if breach:
                    self.breach_ticks += 1
            while budget > 0:
                act = self._nudge_down(hot[0])
                if act is None:
                    break
                actions.append(act)
                budget -= 1
            status = "hot"
        else:
            with self._stats_lock:
                self._cool_streak += 1
                cool_enough = self._cool_streak >= self.cool_windows
            if cool_enough and budget > 0:
                act = self._revert_one("cool")
                if act is not None:
                    actions.append(act)
                    with self._stats_lock:
                        self._cool_streak = 0
            status = "cool"
        if not actions:
            with self._stats_lock:
                self.holds += 1
        return {"status": status, "hot": hot, "recall_burn": recall_burn,
                "actions": actions}

    # -- moves --------------------------------------------------------------
    def _guardrailed(self) -> bool:
        """True while the shadow-recall Wilson CI forbids recall-costing
        moves: ci_low at/under the floor, or no usable estimate at all
        (blindness is not permission)."""
        if self.recall_floor is None:
            return False
        if self.sampler is None:
            return True
        try:
            est = self.sampler.estimate()
        except Exception as e:
            resilience.classify(e)
            return True
        ci_low = est.get("ci_low") if isinstance(est, dict) else None
        if not isinstance(ci_low, (int, float)):
            return True
        return ci_low <= self.recall_floor

    def _nudge_down(self, reason: str) -> Optional[dict]:
        guarded = self._guardrailed()
        for act in self.actuators:
            if act.idx == 0:
                continue  # already at its floor
            if act.costs_recall and guarded:
                with self._stats_lock:
                    self.guardrail_holds += 1
                obs.add("tuning.guardrail_holds")
                record_event("tuning.guardrail_hold", knob=act.name,
                             reason=reason, floor=self.recall_floor)
                continue
            frm, to = act.step(-1)
            return self._record_action(act, "nudge", frm, to, reason)
        return None

    def _revert_one(self, reason: str) -> Optional[dict]:
        """One rung back toward the tuned point, latency-cheapest knob
        last to re-raise (walk the priority list in reverse so the most
        expensive relief is undone first)."""
        for act in reversed(self.actuators):
            i = act.idx
            if i == act.tuned_idx:
                continue
            frm, to = act.step(+1 if i < act.tuned_idx else -1)
            return self._record_action(act, "revert", frm, to, reason)
        return None

    def _revert_recall(self, reason: str) -> Optional[dict]:
        """A burning recall SLO immediately re-raises a recall-costing
        knob sitting below its tuned rung — the one move class exempt
        from the cool-streak hysteresis."""
        for act in reversed(self.actuators):
            if act.costs_recall and act.idx < act.tuned_idx:
                frm, to = act.step(+1)
                return self._record_action(act, "revert", frm, to, reason)
        return None

    def _record_action(self, act: KnobActuator, action: str, frm, to,
                       reason: str) -> dict:
        with self._stats_lock:
            if action == "nudge":
                self.nudges += 1
            else:
                self.reverts += 1
        obs.add(f"tuning.{action}s")
        # the flight recorder folds ring events into the window timeline:
        # this line IS the reconstructible tuning episode
        record_event("tuning.action", knob=act.name, frm=frm, to=to,
                     action=action, reason=reason)
        return {"knob": act.name, "frm": frm, "to": to, "action": action,
                "reason": reason}

    # -- worker -------------------------------------------------------------
    def start(self) -> None:
        """Run the control loop on a daemon worker thread (the bench's
        pump-in-idle-gaps mode stays available for deterministic runs)."""
        if not self._enabled:
            return
        if self._worker is not None and self._worker.is_alive():
            return
        self._stopping = False
        self._worker = threading.Thread(
            target=self._run_loop, name="raft-tpu-controller", daemon=True)
        self._worker.start()

    def _run_loop(self) -> None:
        while not self._stopping:
            self.pump()
            time.sleep(self.interval_s)

    def stop(self, timeout: float = 30.0) -> None:
        if not self._enabled:
            return
        self._stopping = True
        if self._worker is not None:
            self._worker.join(timeout=timeout)
            self._worker = None

    # -- reporting ----------------------------------------------------------
    def report(self) -> Optional[dict]:
        """The obs-report ``tuning`` section (schema v6): the action
        ledger plus where every knob sits relative to its tuned rung."""
        if not self._enabled:
            return None
        knobs = {a.name: a.value for a in self.actuators}
        tuned = {a.name: a.values[a.tuned_idx] for a in self.actuators}
        with self._stats_lock:
            return {
                "ticks": self.ticks,
                "actions": self.nudges + self.reverts,
                "nudges": self.nudges,
                "reverts": self.reverts,
                "holds": self.holds,
                "guardrail_holds": self.guardrail_holds,
                "failures": self.failures,
                "breach_ticks": self.breach_ticks,
                "last_status": self.last_status,
                "cool_streak": self._cool_streak,
                "recall_floor": self.recall_floor,
                "knobs": knobs,
                "tuned": tuned,
            }

    def stats(self) -> Optional[dict]:
        return self.report()
