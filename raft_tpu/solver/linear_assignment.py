"""Linear assignment problem (reference solver/linear_assignment.cuh:54,
the Date–Nagi GPU Hungarian algorithm).

TPU redesign — Bertsekas' auction algorithm with ε-scaling instead of the
Hungarian alternating tree: the Hungarian augmenting-path search is a
sequential pointer chase, while an auction round is three vectorized steps
(every unassigned row bids its top-2 margin, columns take the max bid via a
segment reduction, prices rise). Rounds run under `lax.while_loop`; the
ε-scaling phases guarantee the final assignment is within n·ε_final of
optimal (exact for integer costs when ε_final < 1/n — Bertsekas 1988).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnames=("max_rounds",))
def _auction_phase(benefits, prices, eps, max_rounds: int):
    n = benefits.shape[0]
    NEG = jnp.float32(-jnp.inf)

    def cond(state):
        row_to_col, _, _, rounds = state
        return jnp.any(row_to_col < 0) & (rounds < max_rounds)

    def body(state):
        row_to_col, col_to_row, prices, rounds = state
        unassigned = row_to_col < 0

        v = benefits - prices[None, :]                     # (n, n)
        top2, idx2 = lax.top_k(v, 2)
        jstar = idx2[:, 0]
        bid_amount = prices[jstar] + top2[:, 0] - top2[:, 1] + eps
        bids = jnp.where(unassigned, bid_amount, NEG)

        # column-side: take the highest bid (two-pass segment argmax)
        key = jnp.where(unassigned, jstar, n).astype(jnp.int32)
        best_bid = jax.ops.segment_max(bids, key, num_segments=n + 1)[:n]
        has_bid = jnp.isfinite(best_bid)
        at_best = unassigned & (bids == best_bid[jstar])
        winner = jax.ops.segment_min(
            jnp.where(at_best, jnp.arange(n, dtype=jnp.int32), n),
            key, num_segments=n + 1,
        )[:n]
        winner = jnp.where(has_bid, winner, n)

        # column ownership is authoritative: winners take their column
        # (evicting the previous owner implicitly), and row_to_col is
        # rebuilt from it — a bidding row was unassigned and bids for
        # exactly one column, so ownership stays one-to-one
        col_ids = jnp.arange(n, dtype=jnp.int32)
        new_col_to_row = jnp.where(has_bid, winner, col_to_row)
        pos = jnp.where(new_col_to_row >= 0, new_col_to_row, n)
        row_to_col = jnp.full(n, -1, jnp.int32).at[pos].set(col_ids, mode="drop")

        prices = jnp.where(has_bid, best_bid, prices)
        return row_to_col, new_col_to_row, prices, rounds + 1

    init = (jnp.full(n, -1, jnp.int32), jnp.full(n, -1, jnp.int32), prices,
            jnp.zeros((), jnp.int32))
    row_to_col, col_to_row, prices, _ = lax.while_loop(cond, body, init)
    return row_to_col, prices


def linear_assignment(costs, eps_final: float = 0.0) -> Tuple[jax.Array, jax.Array]:
    """Min-cost perfect assignment of an (n, n) cost matrix.

    Returns ``(row_to_col (n,) int32, total_cost scalar)``. ``eps_final``
    defaults to ``min(cost_range / (2n·(n+1)), 1/(2(n+1)))`` — the second
    term guarantees n·ε < 1/2, so integer costs solve exactly (Bertsekas
    1988); pass a larger value to trade optimality for speed.

    Raises ``RuntimeError`` if the auction fails to assign every row within
    the (escalating) round budget — a partial assignment is never returned
    silently (ADVICE.md round-2 medium finding).
    """
    costs = jnp.asarray(costs, jnp.float32)
    if costs.ndim != 2 or costs.shape[0] != costs.shape[1]:
        raise ValueError(f"costs must be square, got {costs.shape}")
    n = costs.shape[0]
    benefits = -costs
    rng = float(jnp.max(costs) - jnp.min(costs)) or 1.0
    if eps_final <= 0:
        eps_final = min(rng / (2.0 * n * (n + 1)), 1.0 / (2.0 * (n + 1)))

    eps = max(rng / 2.0, eps_final)
    prices = jnp.zeros(n, jnp.float32)
    max_rounds = 50 * n + 1000
    while True:
        row_to_col, prices = _auction_phase(
            benefits, prices, jnp.float32(eps), max_rounds
        )
        if eps <= eps_final:
            break
        eps = max(eps / 5.0, eps_final)

    # the final phase must leave no row unassigned; with finite benefits the
    # auction terminates, so an incomplete result means the round budget was
    # too small — escalate (bounded) rather than return a corrupt total
    for _ in range(3):
        if bool(jnp.all(row_to_col >= 0)):
            break
        max_rounds *= 8
        row_to_col, prices = _auction_phase(
            benefits, prices, jnp.float32(eps_final), max_rounds
        )
    if not bool(jnp.all(row_to_col >= 0)):
        raise RuntimeError(
            "auction failed to assign all rows (non-finite costs?); "
            f"{int(jnp.sum(row_to_col < 0))} rows unassigned"
        )
    total = jnp.sum(costs[jnp.arange(n), jnp.clip(row_to_col, 0, n - 1)])
    return row_to_col, total
