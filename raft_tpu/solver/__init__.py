"""Solvers (reference cpp/include/raft/solver/): linear assignment."""

from raft_tpu.solver.linear_assignment import linear_assignment

__all__ = ["linear_assignment"]
