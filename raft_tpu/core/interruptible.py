"""Cooperative cross-thread cancellation.

Reference: cpp/include/raft/core/interruptible.hpp:71-168 — a per-thread token
that stream-synchronizing loops poll; `cancel()` from another thread raises
`interrupted_exception` at the next synchronization point. The TPU analog:
long-running *host-side* loops (k-means EM, NN-descent rounds, tiled batch
queries) call :func:`check_interrupt` between device steps.
"""

from __future__ import annotations

import threading

_flags: dict = {}
_lock = threading.Lock()


class InterruptedException(RuntimeError):
    """Raised at the next check point after :func:`cancel` (the reference's
    raft::interrupted_exception; named to avoid shadowing the builtin
    InterruptedError, which is an OSError for EINTR)."""


def _token(thread_id=None) -> int:
    return thread_id if thread_id is not None else threading.get_ident()


def cancel(thread_id=None) -> None:
    """Request cancellation of ``thread_id`` (default: current thread)."""
    with _lock:
        _flags[_token(thread_id)] = True


def clear(thread_id=None) -> None:
    with _lock:
        _flags.pop(_token(thread_id), None)


def check_interrupt() -> None:
    """Raise :class:`InterruptedException` if this thread was cancelled."""
    tid = threading.get_ident()
    with _lock:
        if _flags.pop(tid, False):
            raise InterruptedException(f"thread {tid} interrupted")
