"""Cooperative cross-thread cancellation.

Reference: cpp/include/raft/core/interruptible.hpp:71-168 — a per-thread token
that stream-synchronizing loops poll; `cancel()` from another thread raises
`interrupted_exception` at the next synchronization point. The TPU analog:
long-running *host-side* loops (k-means EM, NN-descent rounds, tiled batch
queries) call :func:`check_interrupt` between device steps.

Extension point (ISSUE 3): :func:`add_checkpoint` registers extra checks
that run at every :func:`check_interrupt` site — ``resilience.deadline``
uses it so every existing interrupt checkpoint doubles as a deadline
checkpoint without this module importing (or even knowing about) the
resilience layer.
"""

from __future__ import annotations

import threading
from typing import Callable, List

_flags: dict = {}
_lock = threading.Lock()
_checkpoints: List[Callable] = []


class InterruptedException(RuntimeError):
    """Raised at the next check point after :func:`cancel` (the reference's
    raft::interrupted_exception; named to avoid shadowing the builtin
    InterruptedError, which is an OSError for EINTR)."""


def _token(thread_id=None) -> int:
    return thread_id if thread_id is not None else threading.get_ident()


def cancel(thread_id=None) -> None:
    """Request cancellation of ``thread_id`` (default: current thread)."""
    with _lock:
        _flags[_token(thread_id)] = True


def clear(thread_id=None) -> None:
    with _lock:
        _flags.pop(_token(thread_id), None)


def add_checkpoint(fn: Callable) -> None:
    """Register ``fn()`` to run at every :func:`check_interrupt` call
    (idempotent). ``fn`` raises to stop the checkpointed loop — e.g. the
    resilience layer's deadline check raising ``DeadlineExceeded``."""
    with _lock:
        if fn not in _checkpoints:
            _checkpoints.append(fn)


def check_interrupt() -> None:
    """Raise :class:`InterruptedException` if this thread was cancelled,
    then run the registered checkpoint hooks (deadlines, …)."""
    tid = threading.get_ident()
    with _lock:
        if _flags.pop(tid, False):
            raise InterruptedException(f"thread {tid} interrupted")
        hooks = tuple(_checkpoints)
    for fn in hooks:
        fn()
