"""Logging — analog of the reference's spdlog-backed logger.

Reference: cpp/include/raft/core/logger-inl.hpp:74-89 (callback sink so Python
can capture C++ logs), logger-macros.hpp (RAFT_LOG_*). Here the whole stack is
Python, so we use stdlib logging with the same capability: a process-wide named
logger plus an optional callback sink. :func:`set_level` is the
``RAFT_LOG_LEVEL`` / ``set_log_level`` analog.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Union

_LOGGER_NAME = "raft_tpu"

# One formatter shared by every sink: callback sinks must see the same
# "[LEVEL] [name] msg" rendering as the stream handler (a bare
# self.format(record) with no formatter installed hands callbacks the raw
# message only — the reference's log_callback receives the formatted line).
_FORMATTER = logging.Formatter("[%(levelname)s] [%(name)s] %(message)s")


class _CallbackHandler(logging.Handler):
    def __init__(self, fn: Callable[[int, str], None]):
        super().__init__()
        self.setFormatter(_FORMATTER)
        self._fn = fn

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._fn(record.levelno, self.format(record))
        except Exception:  # pragma: no cover - sink errors must not propagate
            pass


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(_FORMATTER)
        logger.addHandler(handler)
        logger.setLevel(logging.WARNING)
    return logger


def set_level(level: Union[int, str]) -> None:
    """Set the process-wide raft_tpu log level (RAFT_LOG_* analog,
    logger-macros.hpp). Accepts a stdlib level int or a name like "debug"."""
    if isinstance(level, str):
        resolved = logging.getLevelName(level.upper())
        if not isinstance(resolved, int):
            raise ValueError(f"unknown log level {level!r}")
        level = resolved
    get_logger().setLevel(level)


def set_callback_sink(fn: Optional[Callable[[int, str], None]]) -> None:
    """Install (or with None, remove) a callback sink — the analog of the
    reference's log_callback for Python capture (core/logger-inl.hpp:74)."""
    logger = get_logger()
    for h in list(logger.handlers):
        if isinstance(h, _CallbackHandler):
            logger.removeHandler(h)
    if fn is not None:
        logger.addHandler(_CallbackHandler(fn))
