"""Logging — analog of the reference's spdlog-backed logger.

Reference: cpp/include/raft/core/logger-inl.hpp:74-89 (callback sink so Python
can capture C++ logs), logger-macros.hpp (RAFT_LOG_*). Here the whole stack is
Python, so we use stdlib logging with the same capability: a process-wide named
logger plus an optional callback sink.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

_LOGGER_NAME = "raft_tpu"


class _CallbackHandler(logging.Handler):
    def __init__(self, fn: Callable[[int, str], None]):
        super().__init__()
        self._fn = fn

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._fn(record.levelno, self.format(record))
        except Exception:  # pragma: no cover - sink errors must not propagate
            pass


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter("[%(levelname)s] [%(name)s] %(message)s"))
        logger.addHandler(handler)
        logger.setLevel(logging.WARNING)
    return logger


def set_callback_sink(fn: Optional[Callable[[int, str], None]]) -> None:
    """Install (or with None, remove) a callback sink — the analog of the
    reference's log_callback for Python capture (core/logger-inl.hpp:74)."""
    logger = get_logger()
    for h in list(logger.handlers):
        if isinstance(h, _CallbackHandler):
            logger.removeHandler(h)
    if fn is not None:
        logger.addHandler(_CallbackHandler(fn))
