"""Array + container serialization with numpy-compatible headers.

Reference: cpp/include/raft/core/serialize.hpp:36-126 serializes mdspans to an
iostream with a numpy-format dtype header so host tools can read device dumps.
Here we serialize `jax.Array`/`numpy` arrays as standard ``.npy`` payloads inside
a tiny tagged container, so a file written by raft_tpu is readable with plain
numpy — the same interop goal.

Container format (used by every index's serialize/deserialize — the analog of
neighbors/{ivf_flat,ivf_pq,cagra,brute_force}_serialize.cuh):

    magic  b"RAFTTPU\\0"  (8 bytes)
    version uint32 LE
    meta_len uint64 LE, meta = UTF-8 JSON (scalar params, dtype names, order)
    for each array in meta["arrays"]: a standard .npy blob, in order
"""

from __future__ import annotations

import io
import json
import os
import struct
from typing import Any, Dict, Mapping, Tuple

import numpy as np

_MAGIC = b"RAFTTPU\x00"
_VERSION = 1


def serialize_array(stream: io.IOBase, arr) -> None:
    """Write one array as a standard .npy blob (numpy-header format parity with
    reference serialize_mdspan, core/serialize.hpp:91)."""
    np.save(stream, np.asarray(arr), allow_pickle=False)


def deserialize_array(stream: io.IOBase) -> np.ndarray:
    return np.load(stream, allow_pickle=False)


def save_arrays(path_or_stream, meta: Mapping[str, Any], arrays: Mapping[str, Any]) -> None:
    """Save a JSON-meta + named-array container (index checkpoint format)."""
    own = isinstance(path_or_stream, (str, bytes, os.PathLike))
    stream = open(path_or_stream, "wb") if own else path_or_stream
    try:
        meta = dict(meta)
        meta["arrays"] = list(arrays.keys())
        blob = json.dumps(meta).encode("utf-8")
        stream.write(_MAGIC)
        stream.write(struct.pack("<I", _VERSION))
        stream.write(struct.pack("<Q", len(blob)))
        stream.write(blob)
        for name in meta["arrays"]:
            serialize_array(stream, arrays[name])
    finally:
        if own:
            stream.close()


def load_arrays(path_or_stream) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Load a container written by :func:`save_arrays`."""
    own = isinstance(path_or_stream, (str, bytes, os.PathLike))
    stream = open(path_or_stream, "rb") if own else path_or_stream
    try:
        magic = stream.read(8)
        if magic != _MAGIC:
            raise ValueError(f"bad magic {magic!r}: not a raft_tpu container")
        (version,) = struct.unpack("<I", stream.read(4))
        if version > _VERSION:
            raise ValueError(f"unsupported container version {version}")
        try:
            (meta_len,) = struct.unpack("<Q", stream.read(8))
            meta = json.loads(stream.read(meta_len).decode("utf-8"))
            arrays = {name: deserialize_array(stream)
                      for name in meta["arrays"]}
        except ValueError:
            raise
        except Exception as e:
            # np.load's header parser leaks tokenize/struct/unicode errors
            # on garbage bytes past a valid magic — surface one stable
            # exception type for corrupt files
            raise ValueError(f"corrupt raft_tpu container: {e!r}") from e
        return meta, arrays
    finally:
        if own:
            stream.close()
