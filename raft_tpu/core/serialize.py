"""Array + container serialization with numpy-compatible headers.

Reference: cpp/include/raft/core/serialize.hpp:36-126 serializes mdspans to an
iostream with a numpy-format dtype header so host tools can read device dumps.
Here we serialize `jax.Array`/`numpy` arrays as standard ``.npy`` payloads inside
a tiny tagged container, so a file written by raft_tpu is readable with plain
numpy — the same interop goal.

Container format (used by every index's serialize/deserialize — the analog of
neighbors/{ivf_flat,ivf_pq,cagra,brute_force}_serialize.cuh):

    magic  b"RAFTTPU\\0"  (8 bytes)
    version uint32 LE
    meta_len uint64 LE, meta = UTF-8 JSON (scalar params, dtype names, order)
    for each array in meta["arrays"]: a standard .npy blob, in order

Version 2 (crash-safe snapshots, ISSUE 7) hardens both ends of the pipe:

* **write** — path saves go through :func:`raft_tpu.core.fsio.atomic_write`
  (tmp + flush + fsync + rename), so a process killed mid-save leaves the
  previous checkpoint intact, never a torn file; the
  ``serialize.save.write`` faultpoint makes the mid-write kill injectable
  in CPU tier-1.
* **read** — the meta block carries each array's byte length and CRC32.
  A truncated or bit-flipped blob fails the load with
  :class:`SnapshotCorruptError` (a ``ValueError`` that
  ``resilience.classify`` maps to FATAL — never retried) NAMING the bad
  array, instead of whatever tokenizer error ``np.load`` happens to leak.

Version-1 files (no lengths/CRCs) still load through the legacy path.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, Mapping, Tuple

import numpy as np

_MAGIC = b"RAFTTPU\x00"
_VERSION = 2


class SnapshotCorruptError(ValueError):
    """A container failed its integrity check (truncation, CRC mismatch,
    garbage header). Classified FATAL: the bytes are gone — the recovery
    action is *reload from another snapshot*, not a retry."""


def serialize_array(stream: io.IOBase, arr) -> None:
    """Write one array as a standard .npy blob (numpy-header format parity with
    reference serialize_mdspan, core/serialize.hpp:91)."""
    np.save(stream, np.asarray(arr), allow_pickle=False)


def deserialize_array(stream: io.IOBase) -> np.ndarray:
    return np.load(stream, allow_pickle=False)


class _CrcSink(io.RawIOBase):
    """Write sink that folds CRC32 and counts bytes, storing nothing —
    the measuring pass of :func:`save_arrays` at O(1) extra memory."""

    def __init__(self):
        self.count = 0
        self.crc = 0

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        self.crc = zlib.crc32(b, self.crc) & 0xFFFFFFFF
        self.count += len(b)
        return len(b)


def save_arrays(path_or_stream, meta: Mapping[str, Any], arrays: Mapping[str, Any]) -> None:
    """Save a JSON-meta + named-array container (index checkpoint format).

    Path targets are written atomically (fsio.atomic_write); stream targets
    are the caller's durability problem (in-memory round-trips, sockets).

    Lengths + CRCs must land in the meta block, which PRECEDES the payloads
    in the stream — so arrays are serialized twice: a measuring pass into a
    counting sink, then the real write. That costs a second device fetch
    per jax array but never holds more than np.save's own buffering in
    memory; a checkpoint near HBM/host capacity (the incident class this
    format serves) cannot afford a second in-RAM copy of the index."""
    from raft_tpu.core.fsio import atomic_write
    from raft_tpu.resilience import faultpoint

    meta = dict(meta)
    meta["arrays"] = list(arrays.keys())
    meta["array_bytes"] = {}
    meta["array_crc32"] = {}
    for name in meta["arrays"]:
        sink = _CrcSink()
        serialize_array(sink, arrays[name])
        meta["array_bytes"][name] = sink.count
        meta["array_crc32"][name] = sink.crc

    def write_to(stream) -> None:
        blob_meta = json.dumps(meta).encode("utf-8")
        stream.write(_MAGIC)
        stream.write(struct.pack("<I", _VERSION))
        stream.write(struct.pack("<Q", len(blob_meta)))
        stream.write(blob_meta)
        # mid-write injection site: a fatal here proves the atomic contract
        # (target keeps its previous bytes) in CPU tier-1
        faultpoint("serialize.save.write")
        for name in meta["arrays"]:
            serialize_array(stream, arrays[name])

    if isinstance(path_or_stream, (str, bytes, os.PathLike)):
        with atomic_write(path_or_stream) as stream:
            write_to(stream)
    else:
        write_to(path_or_stream)


def _load_v2(stream, meta) -> Dict[str, np.ndarray]:
    """Length- and CRC-checked array reads (v2 containers)."""
    sizes = meta.get("array_bytes", {})
    crcs = meta.get("array_crc32", {})
    arrays: Dict[str, np.ndarray] = {}
    for name in meta["arrays"]:
        want = int(sizes[name])
        blob = stream.read(want)
        if len(blob) < want:
            raise SnapshotCorruptError(
                f"truncated container: array {name!r} has {len(blob)} of "
                f"{want} bytes — partial write, reload from a snapshot")
        got_crc = zlib.crc32(blob) & 0xFFFFFFFF
        if got_crc != int(crcs[name]):
            raise SnapshotCorruptError(
                f"corrupt container: array {name!r} CRC32 {got_crc:#010x} != "
                f"recorded {int(crcs[name]):#010x} — bit corruption, reload "
                f"from a snapshot")
        try:
            arrays[name] = deserialize_array(io.BytesIO(blob))
        except Exception as e:
            raise SnapshotCorruptError(
                f"corrupt container: array {name!r} passed CRC but failed "
                f"npy parse: {e!r}") from e
    return arrays


def load_arrays(path_or_stream) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Load a container written by :func:`save_arrays` (v1 or v2).

    The ``serialize.load.read`` faultpoint (round 18) sits at the
    host-side dispatch point of every container read — index ``load()``s,
    ``distributed/snapshot.restore_shard``, and the capacity plane's
    snapshot-backed promotion all pass through here, so an oom/hang on
    the tunneled runtime's load path is injectable in CPU tier-1 (the
    saves have carried ``serialize.save.write`` since round 9)."""
    from raft_tpu.resilience import faultpoint

    faultpoint("serialize.load.read")
    own = isinstance(path_or_stream, (str, bytes, os.PathLike))
    stream = open(path_or_stream, "rb") if own else path_or_stream
    try:
        magic = stream.read(8)
        if magic != _MAGIC:
            raise ValueError(f"bad magic {magic!r}: not a raft_tpu container")
        head = stream.read(4)
        if len(head) < 4:
            raise SnapshotCorruptError(
                "truncated container: file ends inside the version field")
        (version,) = struct.unpack("<I", head)
        if version > _VERSION:
            raise ValueError(f"unsupported container version {version}")
        try:
            head = stream.read(8)
            if len(head) < 8:
                raise SnapshotCorruptError(
                    "truncated container: file ends inside the meta length")
            (meta_len,) = struct.unpack("<Q", head)
            raw_meta = stream.read(meta_len)
            if len(raw_meta) < meta_len:
                raise SnapshotCorruptError(
                    f"truncated container: meta block has {len(raw_meta)} of "
                    f"{meta_len} bytes")
            meta = json.loads(raw_meta.decode("utf-8"))
            if version >= 2:
                arrays = _load_v2(stream, meta)
            else:
                arrays = {name: deserialize_array(stream)
                          for name in meta["arrays"]}
        except SnapshotCorruptError:
            raise
        except Exception as e:
            # np.load's header parser and the meta json decode leak
            # tokenize/struct/unicode errors on garbage bytes past a valid
            # magic (UnicodeDecodeError/JSONDecodeError are ValueError
            # subclasses — a bare `except ValueError: raise` let them
            # escape unclassified) — surface one stable exception type
            raise SnapshotCorruptError(
                f"corrupt raft_tpu container: {e!r}") from e
        return meta, arrays
    finally:
        if own:
            stream.close()
