"""Version shims for jax API moves.

One function per moved API, resolved once at import. Library code imports
from here instead of feature-testing at every call site; when the minimum
supported jax passes the new spelling, delete the shim and inline the call.
"""

from __future__ import annotations

import jax
from jax import lax

if hasattr(lax, "axis_size"):

    def axis_size(axis: str) -> int:
        return lax.axis_size(axis)

else:  # jax < 0.5: psum of a literal 1 constant-folds to the static size

    def axis_size(axis: str) -> int:
        return lax.psum(1, axis)

if hasattr(jax, "shard_map"):

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)

else:  # jax < 0.5: experimental home, `check_vma` was named `check_rep`
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)
