"""Profiler trace ranges (reference core/nvtx.hpp:25-90 RAII ranges).

`jax.profiler.TraceAnnotation` is the TPU analog of an NVTX range: spans
appear on the host timeline of a `jax.profiler.trace(...)` capture. The
``traced`` decorator is the `RAFT_USING_RANGE`-style entry-point annotation
used across build/search paths; it costs one context manager per call (not
per device op) and nothing when no trace is active.

When telemetry is enabled (``RAFT_TPU_OBS=1`` / :func:`raft_tpu.obs.enable`),
``traced`` routes through :func:`raft_tpu.obs.record_span` instead, which
wraps the same TraceAnnotation AND records the wall-clock duration into the
process-wide metrics registry — every ``@traced`` entry point becomes a
measured span for free. Off-path cost stays one branch.
"""

from __future__ import annotations

import functools

import jax.profiler

from raft_tpu import obs as _obs


class trace_range(jax.profiler.TraceAnnotation):
    """RAII-style range (core/nvtx.hpp range analog):

    with trace_range("ivf_pq::search"):
        ...
    """


def traced(name: str):
    """Decorator wrapping a function body in a named trace range (and, when
    telemetry is on, a registry-fed timing span)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if _obs.enabled():
                with _obs.record_span(name):
                    return fn(*args, **kwargs)
            with jax.profiler.TraceAnnotation(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco
