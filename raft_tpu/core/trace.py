"""Profiler trace ranges (reference core/nvtx.hpp:25-90 RAII ranges).

`jax.profiler.TraceAnnotation` is the TPU analog of an NVTX range: spans
appear on the host timeline of a `jax.profiler.trace(...)` capture. The
``traced`` decorator is the `RAFT_USING_RANGE`-style entry-point annotation
used across build/search paths; it costs one context manager per call (not
per device op) and nothing when no trace is active.
"""

from __future__ import annotations

import functools

import jax.profiler


class trace_range(jax.profiler.TraceAnnotation):
    """RAII-style range (core/nvtx.hpp range analog):

    with trace_range("ivf_pq::search"):
        ...
    """


def traced(name: str):
    """Decorator wrapping a function body in a named trace range."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with jax.profiler.TraceAnnotation(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco
