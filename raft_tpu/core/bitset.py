"""Packed device bitset for search prefiltering.

Reference: cpp/include/raft/core/bitset.cuh:147 — a device bitset consumed by
`bitset_filter` (neighbors/sample_filter.cuh:31) to exclude dataset rows from
ANN search. TPU design: a uint32-packed jnp array; the filter is applied
vectorized (test of k candidate ids per query at once) rather than per-thread.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
@dataclass
class Bitset:
    """Fixed-size bitset over ``[0, n_bits)`` packed into uint32 words."""

    bits: jax.Array  # (ceil(n_bits/32),) uint32
    n_bits: int

    @classmethod
    def create(cls, n_bits: int, default: bool = True) -> "Bitset":
        n_words = (n_bits + 31) // 32
        fill = jnp.uint32(0xFFFFFFFF) if default else jnp.uint32(0)
        return cls(jnp.full((n_words,), fill, dtype=jnp.uint32), n_bits)

    @classmethod
    def from_mask(cls, mask) -> "Bitset":
        """Build from a boolean vector (True = keep)."""
        mask = jnp.asarray(mask, dtype=jnp.bool_)
        n_bits = mask.shape[0]
        n_words = (n_bits + 31) // 32
        pad = n_words * 32 - n_bits
        padded = jnp.concatenate([mask, jnp.zeros((pad,), jnp.bool_)]) if pad else mask
        w = padded.reshape(n_words, 32).astype(jnp.uint32)
        weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[None, :]
        return cls((w * weights).sum(axis=1).astype(jnp.uint32), n_bits)

    def test(self, ids: jax.Array) -> jax.Array:
        """Vectorized membership test; out-of-range ids return False."""
        ids = jnp.asarray(ids)
        word = self.bits[jnp.clip(ids // 32, 0, self.bits.shape[0] - 1)]
        bit = (word >> (ids % 32).astype(jnp.uint32)) & jnp.uint32(1)
        return (bit == 1) & (ids >= 0) & (ids < self.n_bits)

    def set(self, ids, value: bool = True) -> "Bitset":
        """Return a new bitset with ``ids`` set/cleared (functional update).

        Duplicate ids are tolerated: the update goes through a boolean scatter
        (idempotent), then repacks — O(n_bits) but branch-free under jit.
        """
        ids = jnp.asarray(ids)
        touched = jnp.zeros((self.n_bits,), jnp.bool_).at[ids].set(True, mode="drop")
        packed = Bitset.from_mask(touched).bits
        if value:
            return Bitset(self.bits | packed, self.n_bits)
        return Bitset(self.bits & ~packed, self.n_bits)

    def to_mask(self) -> jax.Array:
        weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[None, :]
        bits = ((self.bits[:, None] & weights) != 0).reshape(-1)
        return bits[: self.n_bits]

    def count(self) -> jax.Array:
        return self.to_mask().sum()

    def popcount(self) -> jax.Array:
        """Number of set bits in ``[0, n_bits)`` — SWAR over the packed
        words (O(n_words) VPU work, no unpack to a bool vector).

        ``create(default=True)`` fills tail bits past ``n_bits`` in the
        last word; those are masked off here so the count matches
        :meth:`count` exactly.
        """
        x = self.bits
        tail = self.n_bits % 32
        if tail and x.shape[0]:
            last = x[-1] & jnp.uint32((1 << tail) - 1)
            x = x.at[-1].set(last)
        x = x - ((x >> 1) & jnp.uint32(0x55555555))
        x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
        x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
        per_word = (x * jnp.uint32(0x01010101)) >> 24
        return per_word.astype(jnp.int32).sum()

    def pass_rate(self) -> float:
        """Fraction of ids in ``[0, n_bits)`` that pass — the planner's
        selectivity estimate. Host float: syncs the device once per
        distinct bitset object (cached on the instance), so call it from
        planner code outside jit, never on a traced value."""
        cached = getattr(self, "_pass_rate_cache", None)
        if cached is None:
            n = max(1, self.n_bits)
            cached = float(self.popcount()) / float(n)
            try:
                self._pass_rate_cache = cached
            except AttributeError:
                pass
        return cached

    # pytree protocol
    def tree_flatten(self):
        return (self.bits,), self.n_bits

    @classmethod
    def tree_unflatten(cls, n_bits, children):
        return cls(children[0], n_bits)
