"""Execution context — the TPU-native analog of ``raft::resources``.

Reference: cpp/include/raft/core/resources.hpp:47 (type-erased lazy resource
registry), core/device_resources.hpp:61 (per-GPU specialization: stream, cuBLAS
handles, workspace memory), core/resource/comms.hpp:64 (communicator injection).

On TPU/JAX most of those resources are owned by the runtime (XLA manages streams,
fusion replaces handle-based BLAS, the compiler manages workspace). What remains
context-like is captured here:

  * which devices / default `Mesh` to run on (the COMMUNICATOR analog);
  * a splittable PRNG key stream (the RNG-state resource);
  * workspace/tile-size budget used by tiled algorithms (the
    WORKSPACE_RESOURCE analog, cpp core/resource/workspace_resource.hpp);
  * default compute dtype for matmul-heavy paths (bf16-in/fp32-accum on MXU).

A default global context is created lazily; `use_resources` scopes an override.
All public APIs accept ``res=None`` and fall back to :func:`current_resources`,
mirroring how every reference API takes ``(resources const&, ...)`` first.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass, field, replace
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


@dataclass
class Resources:
    """Execution context for raft_tpu calls.

    Attributes:
      devices: devices to use; defaults to ``jax.devices()``.
      mesh: optional default ``jax.sharding.Mesh`` for distributed algorithms.
      key: base PRNG key; ``next_key()`` splits from it statefully (the analog
        of the mutable ``rng_state`` resource, reference random/rng_state.hpp:28).
      workspace_bytes: soft budget tiled algorithms use to pick tile sizes
        (analog of the workspace memory resource / batch sizing in
        neighbors/detail/knn_brute_force.cuh:78-91).
      compute_dtype: dtype fed to the MXU for distance matmuls. fp32 inputs are
        cast to this for the gemm, with fp32 accumulation.
    """

    devices: Sequence[jax.Device] = field(default_factory=jax.devices)
    mesh: Optional[jax.sharding.Mesh] = None
    key: jax.Array = None  # type: ignore[assignment]
    workspace_bytes: int = 1 << 30
    compute_dtype: jnp.dtype = jnp.float32

    def __post_init__(self):
        if self.key is None:
            self.key = jax.random.key(0)
        self._key_lock = threading.Lock()

    # -- PRNG stream -------------------------------------------------------
    def next_key(self) -> jax.Array:
        """Split and return a fresh PRNG key (stateful, like rng_state advance;
        locked — the global default Resources is shared across threads)."""
        with self._key_lock:
            self.key, sub = jax.random.split(self.key)
        return sub

    def with_seed(self, seed: int) -> "Resources":
        return replace(self, key=jax.random.key(seed))

    # -- device helpers ----------------------------------------------------
    @property
    def device(self) -> jax.Device:
        return self.devices[0]

    @property
    def n_devices(self) -> int:
        return len(self.devices)

    def default_mesh(self, axis_name: str = "data") -> jax.sharding.Mesh:
        """The mesh to run distributed algorithms over (1-D over all devices
        unless an explicit mesh was installed — the `set_comms` analog)."""
        if self.mesh is not None:
            return self.mesh
        return jax.sharding.Mesh(list(self.devices), (axis_name,))


_tls = threading.local()
_default_lock = threading.Lock()
_default: Optional[Resources] = None


def current_resources() -> Resources:
    """Return the innermost scoped Resources, or the lazily-created global one."""
    stack = getattr(_tls, "stack", None)
    if stack:
        return stack[-1]
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Resources()
    return _default


@contextlib.contextmanager
def use_resources(res: Resources):
    """Scope ``res`` as the current context within the ``with`` block."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(res)
    try:
        yield res
    finally:
        stack.pop()
