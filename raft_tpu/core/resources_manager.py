"""Pooled per-device Resources for multi-threaded servers (reference
core/device_resources_manager.hpp:50-95).

The reference hands each server thread a pooled ``device_resources`` with
round-robin stream assignment so handles aren't rebuilt per request. The
JAX analog: one cached :class:`Resources` per device, derived PRNG streams
per checkout (XLA manages streams itself), thread-safe.
"""

from __future__ import annotations

import itertools
import threading
from typing import Optional

import jax

from raft_tpu.core.resources import Resources

_LOCK = threading.Lock()
_POOL: dict = {}
_COUNTER = itertools.count()
_DEFAULTS: dict = {}


def set_resource_defaults(workspace_bytes: Optional[int] = None,
                          compute_dtype=None) -> None:
    """Configure defaults applied to pool entries created afterwards
    (device_resources_manager set_* analog); call before first checkout."""
    with _LOCK:
        if workspace_bytes is not None:
            _DEFAULTS["workspace_bytes"] = int(workspace_bytes)
        if compute_dtype is not None:
            _DEFAULTS["compute_dtype"] = compute_dtype


def get_resources(device: Optional[jax.Device] = None) -> Resources:
    """The pooled Resources for ``device`` (default: jax.devices()[0]) —
    device_resources_manager::get_device_resources analog. Repeated calls
    return the same instance; its PRNG stream is internally locked, so
    concurrent threads can share it."""
    device = device or jax.devices()[0]
    with _LOCK:
        res = _POOL.get(device.id)
        if res is None:
            res = Resources(devices=[device],
                            key=jax.random.key(next(_COUNTER)),
                            **_DEFAULTS)
            _POOL[device.id] = res
        return res


def clear_pool() -> None:
    """Drop all pooled entries (tests / reconfiguration)."""
    with _LOCK:
        _POOL.clear()
