"""Crash-safe file writes: the one atomic-write helper every artifact uses.

Round-5's wedge proved the failure mode (a killed process leaves torn
files); ISSUE 7 closes the remaining exposure: an index checkpoint written
with a plain ``open(path, "wb")`` that dies mid-write leaves a truncated
container whose reload fails with a cryptic ``np.load`` error — the same
unclassified-failure class. :func:`atomic_write` is the shared contract:

    tmp file in the same directory  →  write  →  flush + fsync  →
    ``os.replace`` onto the target

so a crash at ANY point leaves either the previous file or the complete
new one, never a torn one. The bench heartbeat channel
(``bench/progress.py``) carries its own copy of this pattern by design —
it must stay importable by file path in jax-free parents and cannot take
the package import lock; this module is the package-side home for
everything else (index saves, baselines, dataset writers, hnsw export).

Stdlib-only on purpose: ``raft_tpu.analysis`` (no jax) routes its baseline
store through here too.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import tempfile

# per-process uniquifier for atomic_replace tmp names (mkstemp covers
# atomic_write); pid + counter keeps concurrent processes AND threads from
# ever sharing a tmp path — two writers interleaving into one tmp file is
# exactly the torn-write class this module exists to prevent
_COUNTER = itertools.count()


def _prepare(path) -> str:
    path = os.fspath(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    return path


@contextlib.contextmanager
def atomic_write(path, mode: str = "wb"):
    """Context manager yielding a stream whose contents replace ``path``
    atomically on clean exit (unique tmp + flush + fsync + ``os.replace``).
    On any exception the tmp file is removed and ``path`` is untouched.

    The tmp file lives next to the target (same directory, unique
    ``.tmp``-suffixed name) so the final rename never crosses a filesystem
    boundary and concurrent writers to the same target never share a tmp:
    last ``os.replace`` wins with each result complete, never torn."""
    path = _prepare(path)
    if "r" in mode or "+" in mode or "a" in mode:
        raise ValueError(f"atomic_write is write-only, got mode {mode!r}")
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".",
        prefix=os.path.basename(path) + ".", suffix=".tmp")
    try:
        with os.fdopen(fd, mode) as f:
            yield f
            f.flush()
            os.fsync(f.fileno())
        # mkstemp creates 0600; match open()'s umask-honoring default so a
        # snapshot stays readable to whoever could read the old file
        os.chmod(tmp, 0o666 & ~_umask())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def _umask() -> int:
    """The process umask (read-modify-write: stdlib offers no getter)."""
    cur = os.umask(0o022)
    os.umask(cur)
    return cur


def atomic_replace(path, producer) -> None:
    """Call ``producer(tmp_path)`` to materialize the new contents at a
    unique tmp path, then atomically rename onto ``path`` — the variant for
    writers that insist on owning the file themselves (the native hnsw
    writer takes a path, not a stream). ``producer`` must have
    closed/synced the file before returning."""
    path = _prepare(path)
    tmp = f"{path}.{os.getpid()}.{next(_COUNTER)}.tmp"
    try:
        producer(tmp)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
