"""Core runtime: execution context, serialization, logging, bitsets.

TPU-native analog of the reference's core layer (cpp/include/raft/core/):
`raft::resources` / `device_resources` (core/resources.hpp:47,
core/device_resources.hpp:61) become :class:`Resources` — a lightweight context
holding devices, the default sharding mesh, a PRNG key stream and workspace
limits. mdspan/mdarray (core/mdarray.hpp:129) need no analog: `jax.Array` with
row-major layout is the array vocabulary; helpers here cover what jnp doesn't
(numpy-header serialization, packed bitsets, cooperative interruption).
"""

from raft_tpu.core.resources import Resources, current_resources, use_resources
from raft_tpu.core.fsio import atomic_write, atomic_replace
from raft_tpu.core.serialize import (
    SnapshotCorruptError,
    serialize_array,
    deserialize_array,
    save_arrays,
    load_arrays,
)
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.logger import get_logger, set_level
from raft_tpu.core.interruptible import InterruptedException, check_interrupt, cancel, clear

__all__ = [
    "Resources",
    "SnapshotCorruptError",
    "atomic_replace",
    "atomic_write",
    "current_resources",
    "use_resources",
    "serialize_array",
    "deserialize_array",
    "save_arrays",
    "load_arrays",
    "Bitset",
    "get_logger",
    "set_level",
    "InterruptedException",
    "check_interrupt",
    "cancel",
    "clear",
]
