"""Fault tolerance: classify the failure, shrink the work, retry.

The resilience layer the ROADMAP's "heavy traffic from millions of users"
north star presupposes and the round-4/round-5 incidents demanded — four
parts, each usable alone:

* :mod:`~raft_tpu.resilience.errors` — :func:`classify` maps raw
  exceptions to ``OOM | TRANSIENT | DEADLINE | FATAL``; every broad
  ``except`` in bench and distributed paths routes through it (enforced by
  graftlint's ``unclassified-except`` rule).
* :mod:`~raft_tpu.resilience.retry` — :func:`with_retries` (bounded,
  deterministically-jittered backoff for TRANSIENT) and
  :func:`degrade_on_oom` (the adaptive executor that re-runs an OOM'd
  callable at half the tile/chunk size down to a floor), both feeding
  ``resilience.*`` obs counters and the :func:`recent_events` ring.
* :mod:`~raft_tpu.resilience.deadline` — :class:`Deadline` scopes that
  every ``check_interrupt()`` site consults; partial-capable loops return
  degraded results (``dl.degraded``) instead of dying to the watchdog.
* :mod:`~raft_tpu.resilience.faultinject` — :func:`faultpoint` sites armed
  via ``RAFT_TPU_FAULTS=site=oom:1``-style specs, which is what makes all
  of the above testable on CPU in tier-1.
* :mod:`~raft_tpu.resilience.shard_health` — per-shard
  HEALTHY/SUSPECT/LOST registry + minimum-coverage quorum that the
  distributed searches consult so a lost shard degrades coverage
  (partial merge, ``degraded`` marker) instead of failing the query.
"""

from raft_tpu.resilience.deadline import (
    Deadline,
    DeadlineExceeded,
    active_deadline,
    check_deadline,
)
from raft_tpu.resilience.errors import (
    DEADLINE,
    FATAL,
    KINDS,
    OOM,
    TRANSIENT,
    classify,
    is_retryable,
)
from raft_tpu.resilience.faultinject import (
    FaultInjected,
    arm_faults,
    armed_sites,
    clear_faults,
    faultpoint,
)
from raft_tpu.resilience.shard_health import (
    HEALTHY,
    LOST,
    SUSPECT,
    ShardHealth,
    ShardQuorumError,
    reset_shard_health,
    shard_health,
)
from raft_tpu.resilience.retry import (
    RetryPolicy,
    backoff_delays,
    clear_events,
    degrade_on_oom,
    disable_sync,
    enable_sync,
    force_completion,
    recent_events,
    record_event,
    sync_mode,
    with_retries,
)

__all__ = [
    "DEADLINE",
    "Deadline",
    "DeadlineExceeded",
    "FATAL",
    "FaultInjected",
    "HEALTHY",
    "KINDS",
    "LOST",
    "OOM",
    "RetryPolicy",
    "SUSPECT",
    "ShardHealth",
    "ShardQuorumError",
    "TRANSIENT",
    "active_deadline",
    "arm_faults",
    "armed_sites",
    "backoff_delays",
    "check_deadline",
    "classify",
    "clear_events",
    "clear_faults",
    "degrade_on_oom",
    "disable_sync",
    "enable_sync",
    "faultpoint",
    "force_completion",
    "is_retryable",
    "recent_events",
    "record_event",
    "reset_shard_health",
    "shard_health",
    "sync_mode",
    "with_retries",
]
