"""Shard health registry: per-shard HEALTHY/SUSPECT/LOST, quorum policy.

ROADMAP item 4's resilience sub-goal: a lost shard must cost COVERAGE, not
availability. This registry is the availability layer's memory — every
per-shard dispatch failure routes its :func:`raft_tpu.resilience.classify`
verdict here, and every distributed search consults it before the merge:

* **HEALTHY** — serving. The steady state.
* **SUSPECT** — failed its last dispatch with a recoverable kind
  (TRANSIENT / OOM / DEADLINE-slice). Still probed on the next dispatch —
  one clean pass restores HEALTHY, ``suspect_threshold`` consecutive
  failures demote to LOST.
* **LOST** — failed FATAL, or exhausted its suspect strikes. Skipped by
  every dispatch (its candidates are dropped from the top-k merge, the
  result ships ``degraded`` with ``coverage < 1``) until
  :meth:`ShardHealth.mark_recovered` — the recovery action is *reload from
  snapshot* (``distributed/snapshot.py``), not rebuild.

The **quorum policy** bounds how degraded a result may get: when the
surviving shards cover less than ``min_coverage`` of the rows
(``RAFT_TPU_MIN_SHARD_COVERAGE``, default 0.5), the dispatch raises
:class:`ShardQuorumError` instead of returning a mostly-empty top-k —
below quorum a "result" is noise wearing a degraded marker.

State transitions feed ``distributed.shard_lost`` obs counters and the
resilience event ring, so every incident ships observable.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.resilience.errors import FATAL, classify
from raft_tpu.resilience.retry import record_event

HEALTHY = "healthy"
SUSPECT = "suspect"
LOST = "lost"

STATES = (HEALTHY, SUSPECT, LOST)

ENV_MIN_COVERAGE = "RAFT_TPU_MIN_SHARD_COVERAGE"
DEFAULT_MIN_COVERAGE = 0.5

#: the recovery action stamped on every shard-lost event — the snapshot
#: manifest (distributed/snapshot.py) is what makes it cheap
RECOVERY_ACTION = "reload_from_snapshot"


class ShardQuorumError(RuntimeError):
    """Surviving shards cover less than the minimum-coverage quorum.
    Classified FATAL (never retried verbatim): the fix is operator action —
    recover shards from snapshots — not a re-dispatch."""


def _env_min_coverage() -> float:
    raw = os.environ.get(ENV_MIN_COVERAGE, "").strip()
    try:
        val = float(raw) if raw else DEFAULT_MIN_COVERAGE
    except ValueError:
        val = DEFAULT_MIN_COVERAGE
    return min(max(val, 0.0), 1.0)


class ShardHealth:
    """Thread-safe per-shard state registry (shards are mesh-slot ranks)."""

    def __init__(self, suspect_threshold: int = 2,
                 min_coverage: Optional[float] = None):
        self.suspect_threshold = max(1, int(suspect_threshold))
        self.min_coverage = (_env_min_coverage() if min_coverage is None
                             else min(max(float(min_coverage), 0.0), 1.0))
        self._lock = threading.Lock()
        self._states: Dict[int, str] = {}     # guarded-by: _lock
        self._strikes: Dict[int, int] = {}    # guarded-by: _lock
        self._last_kind: Dict[int, str] = {}  # guarded-by: _lock

    # -- queries ------------------------------------------------------------

    def state(self, shard: int) -> str:
        with self._lock:
            return self._states.get(int(shard), HEALTHY)

    def last_kind(self, shard: int) -> str:
        """Failure kind of the shard's most recent reported failure."""
        with self._lock:
            return self._last_kind.get(int(shard), "")

    def lost(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(s for s, st in self._states.items()
                                if st == LOST))

    def serving_mask(self, world: int) -> np.ndarray:
        """(world,) bool: shards eligible to serve the next dispatch
        (everything not LOST — SUSPECT shards get another chance)."""
        with self._lock:
            return np.array([self._states.get(r, HEALTHY) != LOST
                             for r in range(int(world))], dtype=bool)

    def snapshot(self) -> dict:
        """Diagnostic view: {shard: {state, strikes, last_kind}}."""
        with self._lock:
            return {r: {"state": st,
                        "strikes": self._strikes.get(r, 0),
                        "last_kind": self._last_kind.get(r, "")}
                    for r, st in sorted(self._states.items())}

    # -- transitions --------------------------------------------------------

    def report_failure(self, shard: int, exc: BaseException) -> str:
        """Fold one dispatch failure into the shard's state; returns the new
        state. FATAL loses the shard immediately; recoverable kinds mark it
        SUSPECT and demote to LOST after ``suspect_threshold`` consecutive
        strikes."""
        shard = int(shard)
        kind = classify(exc)
        with self._lock:
            strikes = self._strikes.get(shard, 0) + 1
            self._strikes[shard] = strikes
            self._last_kind[shard] = kind
            new = (LOST if kind == FATAL or strikes >= self.suspect_threshold
                   else SUSPECT)
            was = self._states.get(shard, HEALTHY)
            self._states[shard] = new
        record_event("shard_failure", site=f"shard[{shard}]", kind=kind,
                     state=new, strikes=strikes)
        if new == LOST and was != LOST:
            obs.add("distributed.shard_lost")
            record_event("shard_lost", site=f"shard[{shard}]", kind=kind,
                         recovery=RECOVERY_ACTION)
        return new

    def report_success(self, shard: int) -> None:
        """A clean dispatch through this shard: SUSPECT heals to HEALTHY
        and the strike count resets. (LOST shards are never probed, so a
        success report for one is a recovery bug — flagged loudly.)"""
        shard = int(shard)
        with self._lock:
            if self._states.get(shard, HEALTHY) == LOST:
                raise RuntimeError(
                    f"shard {shard} is LOST; recover it via mark_recovered "
                    f"(reload from snapshot), not a success report")
            self._states[shard] = HEALTHY
            self._strikes[shard] = 0

    def mark_lost(self, shard: int, reason: str = "") -> None:
        """Administrative demotion (a coordinator noticed a dead host)."""
        shard = int(shard)
        with self._lock:
            was = self._states.get(shard, HEALTHY)
            self._states[shard] = LOST
            kind = self._last_kind.setdefault(shard, FATAL)
        if was != LOST:
            obs.add("distributed.shard_lost")
            record_event("shard_lost", site=f"shard[{shard}]",
                         kind=kind, reason=reason,
                         recovery=RECOVERY_ACTION)

    def mark_recovered(self, shard: int) -> None:
        """The shard's data is back (snapshot reload): full reinstatement."""
        shard = int(shard)
        with self._lock:
            self._states[shard] = HEALTHY
            self._strikes[shard] = 0
            self._last_kind.pop(shard, None)
        obs.add("distributed.shard_recovered")
        record_event("shard_recovered", site=f"shard[{shard}]",
                     action=RECOVERY_ACTION)

    # -- quorum -------------------------------------------------------------

    def check_quorum(self, coverage: float, context: str = "") -> None:
        """Raise :class:`ShardQuorumError` when ``coverage`` (fraction of
        rows the surviving shards hold) is below the minimum-coverage
        quorum."""
        if coverage < self.min_coverage:
            obs.add("distributed.quorum_lost")
            record_event("quorum_lost", site=context,
                         coverage=round(float(coverage), 4),
                         min_coverage=self.min_coverage,
                         lost=list(self.lost()))
            raise ShardQuorumError(
                f"shard quorum lost{': ' + context if context else ''}: "
                f"surviving shards cover {coverage:.2%} of rows < minimum "
                f"{self.min_coverage:.2%} ({ENV_MIN_COVERAGE}); lost shards "
                f"{list(self.lost())} need recovery ({RECOVERY_ACTION})")


# ---------------------------------------------------------------------------
# process-global registry (one mesh per process in practice)
# ---------------------------------------------------------------------------

_GLOBAL: Optional[ShardHealth] = None  # guarded-by: _GLOBAL_LOCK
_GLOBAL_LOCK = threading.Lock()


def shard_health() -> ShardHealth:
    """The process-global registry the distributed searches consult by
    default (pass an explicit :class:`ShardHealth` to scope one index)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = ShardHealth()
        return _GLOBAL


def reset_shard_health() -> None:
    """Forget all shard state (tests; also re-reads the quorum env knob)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = None
