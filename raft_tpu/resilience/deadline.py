"""Deadline propagation: budget-aware checkpoints for host-side loops.

The round-5 wedge was killed *opaquely*: the driver's watchdog fired after
the whole window burned and nothing inside the process knew a budget
existed. A :class:`Deadline` makes the budget visible from the inside —
host-side loops that already call
:func:`raft_tpu.core.interruptible.check_interrupt` (k-means EM restarts,
nn_descent rounds, cagra build blocks, batch_knn chunk loops) become
deadline checkpoints for free, because entering a Deadline scope registers
a checkpoint hook with ``interruptible``.

Two severities:

* ``hard=True`` (default): an expired deadline raises
  :class:`DeadlineExceeded` (classified DEADLINE) at the next checkpoint —
  the bounded-time-to-verdict guarantee the fault-injection hang tests
  assert.
* ``hard=False``: checkpoints never raise; partial-capable sites poll
  :meth:`Deadline.reached` themselves and break gracefully, calling
  :meth:`Deadline.mark_degraded` so the owner of the scope sees
  ``dl.degraded == True`` plus which sites returned partial results.

Partial-capable sites always poll ``reached()`` at the top of each
iteration *before* their ``check_interrupt()`` call, so even under a hard
deadline the work finished so far is surfaced instead of thrown away —
the raise is the backstop for loops with nothing partial to return.

Usage::

    from raft_tpu import resilience

    with resilience.Deadline(30.0, label="deep10m") as dl:
        vals, ids = batch_knn.search_out_of_core(dataset, queries, k)
    if dl.degraded:
        ...  # partial result: dl.degraded_sites names the loops that cut short
"""

from __future__ import annotations

import math
import threading
import time

from raft_tpu import obs
from raft_tpu.core import interruptible
from raft_tpu.resilience.retry import record_event


class DeadlineExceeded(RuntimeError):
    """Raised at a checkpoint once a hard :class:`Deadline` expires. The
    message carries the ``DEADLINE_EXCEEDED`` token so
    :func:`raft_tpu.resilience.errors.classify` maps it without an import
    cycle."""


_tls = threading.local()


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class Deadline:
    """A wall-clock budget, scoped with ``with`` and consulted at
    checkpoints. Nesting pushes a stack; the innermost scope is the active
    one (an inner scope tighter than its parent behaves as expected; an
    inner scope LOOSER than its parent shadows it — keep inner budgets
    inside outer ones)."""

    def __init__(self, seconds: float, *, hard: bool = True, label: str = ""):
        self.budget_s = float(seconds)
        self.hard = bool(hard)
        self.label = label
        self.degraded = False
        self.degraded_sites: list = []
        self._t_end: float = math.inf

    # -- scope --------------------------------------------------------------
    def __enter__(self) -> "Deadline":
        self._t_end = time.monotonic() + self.budget_s
        _stack().append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:  # unbalanced exit: still remove ourselves
            stack.remove(self)
        return False

    # -- queries ------------------------------------------------------------
    def remaining(self) -> float:
        """Seconds left (+inf before the scope is entered)."""
        return self._t_end - time.monotonic()

    def reached(self) -> bool:
        return self.remaining() <= 0.0

    def check(self) -> None:
        """Raise :class:`DeadlineExceeded` if the budget is spent."""
        if self.reached():
            raise DeadlineExceeded(
                f"DEADLINE_EXCEEDED: {self.label or 'deadline'} budget "
                f"{self.budget_s:g}s spent")

    # -- partial-result marker ----------------------------------------------
    def mark_degraded(self, site: str) -> None:
        """A checkpointed loop cut itself short at ``site`` and is returning
        partial/degraded results under this deadline."""
        self.degraded = True
        self.degraded_sites.append(site)
        obs.add("resilience.deadline.partial")
        record_event("deadline_partial", site=site,
                     label=self.label, budget_s=self.budget_s)


def active_deadline():
    """The innermost active :class:`Deadline` of this thread, or None."""
    stack = _stack()
    return stack[-1] if stack else None


def check_deadline() -> None:
    """Checkpoint: raise :class:`DeadlineExceeded` when the active deadline
    is hard and spent. Soft deadlines never raise here — partial-capable
    sites poll :meth:`Deadline.reached` themselves."""
    dl = active_deadline()
    if dl is not None and dl.hard:
        dl.check()


# every existing check_interrupt() site becomes a deadline checkpoint
interruptible.add_checkpoint(check_deadline)
