"""Recovery policies: bounded retries and adaptive OOM degradation.

Two executors, matched to the two recoverable failure classes of
``resilience.errors``:

* :func:`with_retries` — re-invoke a callable verbatim on TRANSIENT
  failures, with bounded exponential backoff. Jitter is SEEDED and
  deterministic (a hash of ``(seed, attempt)``, no wall-clock or global
  RNG state — the same determinism contract graftlint's ``banned-api``
  rule enforces in kernel modules).
* :func:`degrade_on_oom` — the adaptive degradation executor for OOM:
  re-invoke the callable with a halved tile/chunk/batch size down to a
  floor. TPU-KNN's peak-FLOP/s framing assumes tile sizes are negotiable;
  "Memory Safe Computations with XLA" (PAPERS.md) argues memory-pressure
  failures should renegotiate rather than die — this is that negotiation,
  as a reusable executor wired into the tiled search paths.

Every recovery is observable twice: obs counters
(``resilience.retries.{kind}``, ``resilience.degraded_tile`` — no-ops with
telemetry off) and a small always-on in-process event ring
(:func:`recent_events`) that tests and callers read as the "degraded"
marker without any return-type change.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

from raft_tpu import obs
from raft_tpu.resilience.errors import OOM, RETRYABLE, classify as _classify

# ---------------------------------------------------------------------------
# sync mode: surface async device failures INSIDE the recovery scope
# ---------------------------------------------------------------------------

# JAX dispatch is asynchronous: a jitted call can return before execution,
# and a runtime RESOURCE_EXHAUSTED then raises at the caller's first host
# fetch — OUTSIDE any recovery executor. Sync mode forces completion inside
# each degradation attempt so the OOM is caught where it can be recovered.
# It costs one host sync per wrapped call, which breaks the back-to-back
# dispatch amortization benched hot paths rely on — so it is OFF by default
# and switched on for recovery-critical runs (RAFT_TPU_RESILIENCE_SYNC=1).
# Injected faults raise eagerly at the faultpoint and need no sync; bench
# sections recover late-surfacing OOMs via their classified section guards
# (deep10m's degraded-scale retry) regardless of this setting.
_sync = os.environ.get("RAFT_TPU_RESILIENCE_SYNC", "").strip().lower() in (
    "1", "true", "on", "yes",
)


def sync_mode() -> bool:
    return _sync


def enable_sync() -> None:
    global _sync
    _sync = True


def disable_sync() -> None:
    global _sync
    _sync = False


def force_completion(tree):
    """Force execution of every array in ``tree`` via a SCALAR HOST FETCH
    and return ``tree``. This is the only force that synchronizes on the
    tunneled axon runtime — ``block_until_ready`` does not (bench.py's
    timing note; cagra's ``_sync``). Execution errors (RESOURCE_EXHAUSTED
    included) raise here, inside the caller's recovery scope."""
    import jax
    import jax.numpy as jnp

    for leaf in jax.tree.leaves(tree):
        float(jnp.sum(leaf))
    return tree

# ---------------------------------------------------------------------------
# event ring: the lightweight "what degraded?" side-channel
# ---------------------------------------------------------------------------

_EVENTS: deque = deque(maxlen=256)
_EV_LOCK = threading.Lock()


def record_event(event: str, site: str = "", **detail) -> None:
    """Append one structured recovery event (thread-safe, bounded ring).
    Events are timestamped so trace exports (obs/tracing.chrome_trace) can
    place them as instant markers alongside the span timeline."""
    rec = {"event": event, "site": site, "t": round(time.time(), 6), **detail}
    with _EV_LOCK:
        _EVENTS.append(rec)


def recent_events() -> list:
    """Snapshot of the recovery-event ring, oldest first."""
    with _EV_LOCK:
        return list(_EVENTS)


def clear_events() -> None:
    with _EV_LOCK:
        _EVENTS.clear()


# ---------------------------------------------------------------------------
# bounded retry with deterministic backoff
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with seeded jitter.

    ``retry_on`` names the failure kinds eligible for verbatim re-invocation
    (default: TRANSIENT only — OOM goes through :func:`degrade_on_oom`,
    DEADLINE/FATAL always propagate).
    """

    max_retries: int = 3
    base_delay_s: float = 0.1
    max_delay_s: float = 5.0
    multiplier: float = 2.0
    jitter: float = 0.25  # ± fraction of the nominal delay
    seed: int = 0
    retry_on: Tuple[str, ...] = RETRYABLE


def _jitter_frac(seed: int, attempt: int) -> float:
    """Deterministic value in [0, 1) from (seed, attempt) — a hash, not a
    clock or global RNG, so the same policy always sleeps the same
    schedule (reproducible benches, replayable failure tests)."""
    h = hashlib.blake2b(f"{seed}:{attempt}".encode(), digest_size=8).digest()
    return int.from_bytes(h, "big") / 2.0 ** 64


def backoff_delays(policy: RetryPolicy) -> list:
    """The full delay schedule (seconds) a policy will sleep, attempt by
    attempt — pure function of the policy, exposed for tests."""
    out = []
    for attempt in range(max(0, policy.max_retries)):
        nominal = min(policy.max_delay_s,
                      policy.base_delay_s * policy.multiplier ** attempt)
        frac = _jitter_frac(policy.seed, attempt)  # [0, 1)
        out.append(max(0.0, nominal * (1.0 + policy.jitter * (2.0 * frac - 1.0))))
    return out


def with_retries(
    fn: Callable,
    policy: RetryPolicy = RetryPolicy(),
    *,
    site: str = "",
    classify: Callable = _classify,
    on_retry: Optional[Callable] = None,
    sleep: Callable = time.sleep,
):
    """Invoke ``fn()``; on a retryable-kind failure, back off and retry up
    to ``policy.max_retries`` times. Non-retryable kinds (and exhausted
    budgets) re-raise the original exception unchanged.

    ``on_retry(exc, kind, attempt)`` is called before each sleep; ``sleep``
    is injectable so tests assert the schedule without waiting it out.
    """
    delays = backoff_delays(policy)
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:
            kind = classify(e)
            if kind not in policy.retry_on or attempt >= len(delays):
                raise
            obs.add(f"resilience.retries.{kind}")
            record_event("retry", site=site, kind=kind, attempt=attempt,
                         error=repr(e)[:200])
            if on_retry is not None:
                on_retry(e, kind, attempt)
            sleep(delays[attempt])
            attempt += 1


# ---------------------------------------------------------------------------
# adaptive OOM degradation
# ---------------------------------------------------------------------------


def degrade_on_oom(
    fn: Callable,
    size: int,
    *,
    floor: int = 1,
    factor: int = 2,
    site: str = "",
    classify: Callable = _classify,
):
    """Adaptive degradation executor: call ``fn(size)``; when it fails with
    an OOM-classified error, halve ``size`` (integer ``// factor``) and
    re-invoke, down to ``floor``. At the floor the error propagates — the
    workload genuinely does not fit.

    ``fn`` must be size-idempotent: any ``size`` in [floor, size] yields a
    correct (if differently-tiled) result. That holds for every wired site
    — tile/chunk row counts only change the work partitioning, never the
    math. Each step is recorded via ``resilience.retries.oom`` /
    ``resilience.degraded_tile`` counters and a ``degraded_tile`` event
    carrying the from→to sizes.

    Under :func:`sync_mode`, each attempt's result is forced to completion
    before the executor returns, so OOMs from ASYNC device execution are
    recovered here too (default-off: the force is a host sync per call —
    see the sync-mode note at the top of this module).
    """
    size = int(size)
    floor = max(1, int(floor))
    while True:
        try:
            out = fn(size)
            if _sync:
                force_completion(out)
            return out
        except Exception as e:
            if classify(e) != OOM or size <= floor:
                raise
        new_size = max(floor, size // max(2, int(factor)))
        obs.add("resilience.retries.oom")
        obs.add("resilience.degraded_tile")
        record_event("degraded_tile", site=site, from_size=size,
                     to_size=new_size)
        size = new_size
