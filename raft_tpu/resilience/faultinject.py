"""Deterministic fault injection: named sites, armed by env or API.

Every recovery path in this package is testable on CPU in tier-1 because
the failures are injectable: hot paths carry zero-cost
``faultpoint("site.name")`` markers that, when armed, raise a simulated
failure of a chosen class on a chosen hit. Disarmed cost is one global
read and one truthiness check — no parsing, no dict lookup, no allocation.

Arming grammar (``RAFT_TPU_FAULTS`` env var, or :func:`arm_faults`)::

    RAFT_TPU_FAULTS="site=kind[:count[:arg]][,site2=kind2...]"

    kind    one of  oom | transient | fatal | delay | hang
    count   how many hits fire, starting from the first (default 1);
            after ``count`` firings the site passes normally
    arg     kind-specific: delay = seconds to sleep (default 0.05),
            hang = max seconds to hang (safety cap, default 300)

Examples::

    batch_knn.search_device_chunked=oom:1      # first hit OOMs, rest pass
    ivf_pq.search.scan=transient:2             # first two hits UNAVAILABLE
    brute_force.search=hang:1:10               # hangs ≤10 s (deadline-bounded)

``oom`` raises with a ``RESOURCE_EXHAUSTED`` message and ``transient``
with ``UNAVAILABLE`` so :func:`raft_tpu.resilience.errors.classify` routes
them exactly like the real thing. ``hang`` spins on
:func:`~raft_tpu.core.interruptible.check_interrupt` — under a hard
:class:`~raft_tpu.resilience.deadline.Deadline` it raises
``DeadlineExceeded`` at expiry, which is how the hang tests prove
time-to-verdict stays bounded without a TPU or a real wedge.

Site naming convention: ``<module>.<entry>[.<phase>]`` —
``ivf_pq.search.scan``, ``batch_knn.search_out_of_core.chunk``,
``distributed.tiled_search.tile``, ``comms.init_distributed``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from raft_tpu import obs
from raft_tpu.core.interruptible import check_interrupt
from raft_tpu.resilience.retry import record_event

ENV_VAR = "RAFT_TPU_FAULTS"

_KINDS = ("oom", "transient", "fatal", "delay", "hang")
_DEFAULT_ARGS = {"delay": 0.05, "hang": 300.0}


class FaultInjected(RuntimeError):
    """A simulated failure raised by an armed :func:`faultpoint`."""


class _Fault:
    __slots__ = ("kind", "remaining", "arg")

    def __init__(self, kind: str, remaining: int, arg: float):
        self.kind = kind
        self.remaining = remaining
        self.arg = arg


# None = env not parsed yet; {} = parsed, nothing armed (the common case)
_SITES: Optional[Dict[str, _Fault]] = None
_LOCK = threading.Lock()


def _parse(spec: str) -> Dict[str, _Fault]:
    table: Dict[str, _Fault] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        site, sep, rhs = entry.partition("=")
        site = site.strip()
        if not sep or not site:
            raise ValueError(f"bad fault entry {entry!r}: want site=kind[:count[:arg]]")
        parts = rhs.strip().split(":")
        kind = parts[0]
        if kind not in _KINDS:
            raise ValueError(f"bad fault kind {kind!r} (known: {', '.join(_KINDS)})")
        count = int(parts[1]) if len(parts) > 1 and parts[1] else 1
        arg = (float(parts[2]) if len(parts) > 2 and parts[2]
               else _DEFAULT_ARGS.get(kind, 0.0))
        table[site] = _Fault(kind, count, arg)
    return table


def arm_faults(spec: str) -> None:
    """Arm faults programmatically (same grammar as the env var)."""
    global _SITES
    with _LOCK:
        _SITES = _parse(spec)


def clear_faults() -> None:
    """Disarm everything (also forgets any env-derived arming)."""
    global _SITES
    with _LOCK:
        _SITES = {}


def reset() -> None:
    """Forget the cached table; the next :func:`faultpoint` re-reads
    ``RAFT_TPU_FAULTS`` (tests that set the env var call this)."""
    global _SITES
    with _LOCK:
        _SITES = None


def armed_sites() -> Dict[str, tuple]:
    """{site: (kind, remaining)} of currently-armed faults (diagnostics)."""
    with _LOCK:
        table = _SITES or {}
        return {s: (f.kind, f.remaining) for s, f in table.items()}


def _fire(site: str, fault: _Fault) -> None:
    obs.add(f"resilience.faults.{fault.kind}")
    record_event("fault_injected", site=site, kind=fault.kind)
    if fault.kind == "oom":
        raise FaultInjected(
            f"RESOURCE_EXHAUSTED: injected oom at faultpoint {site!r}")
    if fault.kind == "transient":
        raise FaultInjected(
            f"UNAVAILABLE: injected transient fault at faultpoint {site!r}")
    if fault.kind == "fatal":
        raise FaultInjected(f"injected fatal fault at faultpoint {site!r}")
    if fault.kind == "delay":
        time.sleep(fault.arg)
        return
    # hang: spin on the cooperative checkpoint — a hard Deadline (or a
    # cross-thread cancel) raises out of check_interrupt; the cap bounds
    # the un-deadlined case so a misconfigured test cannot wedge tier-1
    t0 = time.monotonic()
    while time.monotonic() - t0 < fault.arg:
        check_interrupt()
        time.sleep(0.02)
    raise FaultInjected(
        f"injected hang at faultpoint {site!r} hit its {fault.arg:g}s cap "
        f"with no deadline/interrupt — timed out")


def faultpoint(site: str) -> None:
    """Named injection site. No-op (one global read + truthiness check)
    unless :data:`ENV_VAR` / :func:`arm_faults` armed a fault for exactly
    this site name, in which case the armed behavior fires on each of its
    first ``count`` hits."""
    global _SITES
    table = _SITES
    if table is None:
        with _LOCK:
            if _SITES is None:
                _SITES = _parse(os.environ.get(ENV_VAR, ""))
            table = _SITES
    if not table:
        return
    fault = table.get(site)
    if fault is None:
        return
    with _LOCK:
        if fault.remaining <= 0:
            return
        fault.remaining -= 1
    _fire(site, fault)
