"""Failure classification: every broad handler routes through one table.

The repo's two worst incidents were both *unclassified* failures: round 5
lost the whole bench window to a wedged TPU tunnel (``BENCH_r05.json``
rc=124 — a DEADLINE-class hang) and round 4 lost the DEEP-10M section to
``RESOURCE_EXHAUSTED`` near HBM capacity (an OOM-class failure that a
halved tile size would have survived). Both were stamped ``repr(e)[:300]``
and thrown away. "Memory Safe Computations with XLA" (PAPERS.md) argues the
memory-pressure class should be handled structurally; the prerequisite is
telling the classes apart.

:func:`classify` maps a raw exception to one of four kinds:

* ``OOM``       — device/host allocation failure (``RESOURCE_EXHAUSTED``,
  ``MemoryError``): retryable at a REDUCED size (retry.degrade_on_oom).
* ``TRANSIENT`` — connection resets, ``UNAVAILABLE``/``ABORTED`` runtime
  states, interrupted syscalls: retryable as-is with backoff.
* ``DEADLINE``  — budget expiry (``subprocess.TimeoutExpired``, the
  resilience ``Deadline``, cooperative interrupts): NOT retryable inside
  the expired scope; callers surface partial/degraded results.
* ``FATAL``     — everything else (shape errors, bad params, real bugs):
  never retried, always re-raised.

Classification is type-first, then message-pattern (XLA errors cross the
jaxlib boundary as ``XlaRuntimeError`` with a gRPC-style status prefix, so
string matching is the stable contract), then the ``__cause__`` chain —
wrapped errors keep their class.
"""

from __future__ import annotations

import subprocess

from raft_tpu.core.interruptible import InterruptedException

#: the four failure kinds (values are the spelling used in obs counter
#: names: ``resilience.retries.oom``, ``bench.section_error.transient``, …)
OOM = "oom"
TRANSIENT = "transient"
DEADLINE = "deadline"
FATAL = "fatal"

KINDS = (OOM, TRANSIENT, DEADLINE, FATAL)

#: kinds that with_retries may retry as-is (OOM retries only through the
#: size-reducing degradation executor, never verbatim)
RETRYABLE = (TRANSIENT,)

# message patterns, matched case-insensitively against str(exc). Order
# matters: OOM outranks DEADLINE outranks TRANSIENT (an OOM inside a timed
# scope is still an OOM — shrinking the work is the right response).
_OOM_PATTERNS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "out_of_memory",
    "allocation failure",
    "failed to allocate",
    "hbm limit",
)
_DEADLINE_PATTERNS = (
    "deadline_exceeded",
    "deadline exceeded",
    "timed out",
    "timeout",
)
_TRANSIENT_PATTERNS = (
    "unavailable",
    "aborted",
    "connection reset",
    "connection refused",
    "connection closed",
    "broken pipe",
    "socket closed",
    "temporarily unavailable",
    "try again",
    "transient",
)

# exception type NAMES matched without importing their defining modules
# (jaxlib's XlaRuntimeError moves between modules across jax versions; the
# name is the stable part)
_DEADLINE_TYPE_NAMES = {"DeadlineExceeded", "TimeoutExpired", "TimeoutError"}


def _classify_one(exc: BaseException) -> str:
    """Classify one exception, ignoring its cause chain."""
    if isinstance(exc, MemoryError):
        return OOM
    if isinstance(exc, (subprocess.TimeoutExpired, TimeoutError)):
        return DEADLINE
    if isinstance(exc, InterruptedException):
        # a cooperative cancel is a budget decision by another thread —
        # handled like an expired deadline (stop, surface partials), never
        # retried
        return DEADLINE
    if isinstance(exc, ConnectionError):  # reset / refused / broken pipe
        return TRANSIENT
    if isinstance(exc, InterruptedError):  # EINTR
        return TRANSIENT
    if type(exc).__name__ in _DEADLINE_TYPE_NAMES:
        return DEADLINE
    msg = str(exc).lower()
    if any(p in msg for p in _OOM_PATTERNS):
        return OOM
    if any(p in msg for p in _DEADLINE_PATTERNS):
        return DEADLINE
    if any(p in msg for p in _TRANSIENT_PATTERNS):
        return TRANSIENT
    return FATAL


def classify(exc: BaseException) -> str:
    """Map ``exc`` to ``OOM | TRANSIENT | DEADLINE | FATAL``.

    Walks a bounded ``__cause__`` chain so an EXPLICITLY wrapped
    ``RESOURCE_EXHAUSTED`` (``raise X from oom``) still classifies as OOM
    instead of FATAL. The implicit ``__context__`` chain is deliberately
    NOT walked: a genuine bug raised while *handling* a retryable error
    must stay FATAL, not inherit the retryable class and get re-run.
    """
    seen = 0
    cur: BaseException | None = exc
    while cur is not None and seen < 5:
        kind = _classify_one(cur)
        if kind != FATAL:
            return kind
        cur = cur.__cause__
        seen += 1
    return FATAL


def is_retryable(kind: str) -> bool:
    """True for kinds :func:`~raft_tpu.resilience.retry.with_retries` may
    re-invoke verbatim (OOM is recoverable too, but only through the
    size-reducing degradation executor)."""
    return kind in RETRYABLE
