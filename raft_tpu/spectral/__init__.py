"""Spectral graph partitioning (reference cpp/include/raft/spectral/):
partition via Laplacian eigenvectors + k-means, modularity clustering, and
partition quality analysis."""

from raft_tpu.spectral.partition import analyze_partition, fit_embedding, partition

__all__ = ["analyze_partition", "fit_embedding", "partition"]
