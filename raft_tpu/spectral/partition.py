"""Spectral partition (reference spectral/partition.cuh:49 → detail:
Laplacian smallest eigenvectors via Lanczos → k-means on the embedding;
analysis via edge-cut cost, spectral/partition.cuh analyze_partition).

Composes the framework's own tiers exactly like the reference composes its
own: sparse Laplacian (sparse/linalg.py) → Lanczos (sparse/solver.py) →
k-means (cluster/kmeans.py). All stages are jit-able; the eigen baseline for
tests is numpy's dense eigh.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.cluster import kmeans
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.sparse.convert import coo_to_csr
from raft_tpu.sparse.linalg import laplacian
from raft_tpu.sparse.solver import lanczos_smallest
from raft_tpu.sparse.types import COO


def fit_embedding(
    graph: COO,
    n_components: int,
    normalized: bool = True,
    max_iters: int = 0,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Smallest-eigenpair Laplacian embedding (spectral/eigen_solvers.cuh
    lanczos_solver_t analog). Returns (eigenvalues (k,), vectors (n, k))."""
    n = graph.shape[0]
    if not 0 < n_components < n:
        raise ValueError(f"need 0 < n_components < {n}")
    lap = coo_to_csr(laplacian(graph, normalized=normalized))
    return lanczos_smallest(lap, n_components, max_iters=max_iters, seed=seed)


def partition(
    graph: COO,
    n_clusters: int,
    n_eigenvecs: int = 0,
    normalized: bool = True,
    seed: int = 0,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Spectral graph partition (spectral/partition.cuh:49).

    Returns ``(labels (n,), eigenvalues, eigenvectors)``. ``n_eigenvecs``
    defaults to ``n_clusters`` (the reference's EigenSolver config).
    """
    res = res or current_resources()
    k = int(n_eigenvecs) or int(n_clusters)
    evals, evecs = fit_embedding(graph, k, normalized=normalized, seed=seed)
    # row-normalize the embedding (standard for normalized spectral
    # clustering; the reference's kmeans cluster solver does the same scale
    # normalization)
    emb = evecs / jnp.maximum(jnp.linalg.norm(evecs, axis=1, keepdims=True), 1e-12)
    labels, _ = kmeans.fit_predict(
        emb, kmeans.KMeansParams(n_clusters=int(n_clusters), seed=seed), res=res
    )
    return labels, evals, evecs


def analyze_partition(graph: COO, labels) -> Tuple[jax.Array, jax.Array]:
    """(edge_cut_weight, cost) of a partition (spectral/partition.cuh
    analyzePartition): cost = Σ_i (edges cut by part i) / |part i|."""
    labels = jnp.asarray(labels, jnp.int32)
    n = graph.shape[0]
    lu = labels[jnp.clip(graph.rows, 0, n - 1)]
    lv = labels[jnp.clip(graph.cols, 0, n - 1)]
    cut_e = graph.valid & (lu != lv)
    # both directions present → each undirected cut edge counted twice
    edge_cut = jnp.sum(jnp.where(cut_e, graph.vals, 0)) / 2.0
    n_parts = jnp.max(labels) + 1
    k = labels.shape[0]  # static upper bound for segment count
    part_sizes = jnp.bincount(labels, length=k)
    cut_per_part = jax.ops.segment_sum(
        jnp.where(cut_e, graph.vals, 0.0), jnp.clip(lu, 0, k - 1), num_segments=k
    )
    cost = jnp.sum(jnp.where(part_sizes > 0,
                             cut_per_part / jnp.maximum(part_sizes, 1), 0.0))
    return edge_cut, cost
