"""Monotonic label relabeling (label/classlabels.cuh:91 analog).

TPU design: rank-by-sorted-unique. The reference builds a class array with a
device scan + binary search; here a single sort + prefix count gives each
distinct label its dense rank, and a searchsorted maps every element — all
static-shape, jit-safe, with the unique count returned as a traced scalar.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def make_monotonic(labels, ignore_value: int | None = None) -> Tuple[jax.Array, jax.Array]:
    """Relabel arbitrary int labels to dense 0..n_unique-1 (order of first
    sorted appearance). Returns ``(monotonic (n,), n_unique scalar)``.

    Entries equal to ``ignore_value`` keep -1 and don't count as a class.
    """
    labels = jnp.asarray(labels)
    if labels.ndim != 1:
        raise ValueError(f"labels must be 1-D, got {labels.shape}")
    if ignore_value is not None:
        big = jnp.iinfo(labels.dtype).max
        work = jnp.where(labels == ignore_value, big, labels)
    else:
        work = labels
    s = jnp.sort(work)
    is_new = jnp.concatenate([jnp.array([True]), s[1:] != s[:-1]])
    if ignore_value is not None:
        is_new &= s != jnp.iinfo(labels.dtype).max
    ranks = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    out = ranks[jnp.searchsorted(s, work)]
    n_unique = ranks[-1] + 1
    if ignore_value is not None:
        out = jnp.where(labels == ignore_value, -1, out)
    return out.astype(jnp.int32), n_unique


def get_classes(labels) -> Tuple[jax.Array, jax.Array]:
    """Sorted distinct labels, padded with the max label value
    (label/classlabels.cuh getUniquelabels analog). Returns
    ``(classes (n,) padded, n_unique scalar)`` — static shape, so the padded
    tail repeats the largest class."""
    labels = jnp.asarray(labels)
    s = jnp.sort(labels)
    is_new = jnp.concatenate([jnp.array([True]), s[1:] != s[:-1]])
    n_unique = jnp.sum(is_new.astype(jnp.int32))
    # stable-compact the distinct values to the front, pad tail with the max
    order = jnp.argsort(~is_new, stable=True)
    classes = jnp.where(jnp.arange(s.shape[0]) < n_unique, s[order], s[-1])
    return classes, n_unique


def merge_labels(labels_a, labels_b) -> jax.Array:
    """Merge two labelings: elements sharing a label in either input end up
    in the same output label (label/merge_labels.cuh analog — its use case
    is stitching connected-components halves).

    Implemented as connected components over the bipartite label graph via
    min-pointer hops on a union array, O(log n) sweeps.
    """
    a = jnp.asarray(labels_a, jnp.int32)
    b = jnp.asarray(labels_b, jnp.int32)
    if a.shape != b.shape or a.ndim != 1:
        raise ValueError("labels_a/labels_b must be equal-length 1-D arrays")
    a, _ = make_monotonic(a)
    b, _ = make_monotonic(b)
    n = a.shape[0]
    # representative per element: min element index reachable via shared
    # a-labels or shared b-labels; iterate to fixpoint
    def body(state):
        rep, _ = state
        min_a = jax.ops.segment_min(rep, a, num_segments=n)
        min_b = jax.ops.segment_min(rep, b, num_segments=n)
        new = jnp.minimum(rep, jnp.minimum(min_a[a], min_b[b]))
        return new, jnp.any(new != rep)

    rep, _ = jax.lax.while_loop(
        lambda s: s[1], body, (jnp.arange(n, dtype=jnp.int32), jnp.array(True))
    )
    out, _ = make_monotonic(rep)
    return out
