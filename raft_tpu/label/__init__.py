"""Label utilities (reference cpp/include/raft/label/): monotonic relabeling
and label merging — sort/searchsorted formulations instead of the reference's
device hash kernels (label/classlabels.cuh:91, label/merge_labels.cuh)."""

from raft_tpu.label.classlabels import get_classes, make_monotonic, merge_labels

__all__ = ["get_classes", "make_monotonic", "merge_labels"]
