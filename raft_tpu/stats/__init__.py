"""Statistics & metrics (reference cpp/include/raft/stats/)."""

from raft_tpu.stats.metrics import (
    accuracy,
    adjusted_rand_index,
    completeness_score,
    contingency_matrix,
    homogeneity_score,
    mutual_info_score,
    neighborhood_recall,
    r2_score,
    rand_index,
    regression_metrics,
    silhouette_score,
    trustworthiness_score,
    v_measure,
)
from raft_tpu.stats.summary import (
    cov,
    dispersion,
    entropy,
    histogram,
    information_criterion,
    kl_divergence,
    mean,
    mean_add,
    mean_center,
    meanvar,
    minmax,
    stddev,
    sum_,
    vars_,
    weighted_mean,
)

__all__ = [
    "accuracy", "adjusted_rand_index", "completeness_score",
    "contingency_matrix", "homogeneity_score", "mutual_info_score",
    "neighborhood_recall", "r2_score", "rand_index", "regression_metrics",
    "silhouette_score", "trustworthiness_score", "v_measure",
    "cov", "dispersion", "entropy", "histogram", "information_criterion",
    "kl_divergence", "mean", "mean_add", "mean_center", "meanvar", "minmax",
    "stddev", "sum_", "vars_", "weighted_mean",
]
