"""ML evaluation metrics (reference cpp/include/raft/stats/).

Clustering-comparison metrics are all derived from one contingency matrix,
computed as a one-hot matmul so the scatter runs on the MXU
(stats/contingency_matrix.cuh builds it with atomics; here it is
``onehot(true).T @ onehot(pred)``). Silhouette tiles the pairwise-distance
matrix through cluster-indicator matmuls (stats/silhouette_score.cuh);
trustworthiness ranks original-space neighbors of the embedding
(stats/trustworthiness_score.cuh); neighborhood_recall reproduces the
eps-relative distance-tie matching of stats/detail/neighborhood_recall.cuh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.ops import distance as dist_mod
from raft_tpu.ops.linalg import gemm


def accuracy(predictions, references) -> jax.Array:
    """Fraction of exact matches (stats/accuracy.cuh)."""
    p = jnp.asarray(predictions)
    r = jnp.asarray(references)
    return jnp.mean((p == r).astype(jnp.float32))


def contingency_matrix(
    labels_true, labels_pred,
    n_classes_true: Optional[int] = None,
    n_classes_pred: Optional[int] = None,
) -> jax.Array:
    """(n_classes_true, n_classes_pred) int32 co-occurrence counts
    (stats/contingency_matrix.cuh). Labels must be in [0, n_classes)."""
    t = jnp.asarray(labels_true).ravel()
    p = jnp.asarray(labels_pred).ravel()
    nt = int(n_classes_true) if n_classes_true else int(jnp.max(t)) + 1
    np_ = int(n_classes_pred) if n_classes_pred else int(jnp.max(p)) + 1
    oh_t = (t[:, None] == jnp.arange(nt)[None, :]).astype(jnp.float32)
    oh_p = (p[:, None] == jnp.arange(np_)[None, :]).astype(jnp.float32)
    return gemm(oh_t, oh_p, transpose_a=True).astype(jnp.int32)


def rand_index(labels_true, labels_pred) -> jax.Array:
    """Rand index: fraction of concordant pairs (stats/rand_index.cuh)."""
    c = contingency_matrix(labels_true, labels_pred).astype(jnp.float32)
    n = jnp.sum(c)
    sum_sq = jnp.sum(c * c)
    sum_rows = jnp.sum(jnp.sum(c, axis=1) ** 2)
    sum_cols = jnp.sum(jnp.sum(c, axis=0) ** 2)
    # pairs: a = agreements-in-both, b = disagreements-in-both
    a = (sum_sq - n) / 2.0
    b = (n * n + sum_sq - sum_rows - sum_cols) / 2.0
    total = n * (n - 1.0) / 2.0
    return ((a + b) / total).astype(jnp.float32)


def adjusted_rand_index(labels_true, labels_pred) -> jax.Array:
    """Chance-adjusted Rand index (stats/adjusted_rand_index.cuh)."""
    c = contingency_matrix(labels_true, labels_pred).astype(jnp.float32)
    n = jnp.sum(c)

    def comb2(x):
        return x * (x - 1.0) / 2.0

    sum_comb = jnp.sum(comb2(c))
    sum_a = jnp.sum(comb2(jnp.sum(c, axis=1)))
    sum_b = jnp.sum(comb2(jnp.sum(c, axis=0)))
    expected = sum_a * sum_b / comb2(n)
    max_index = (sum_a + sum_b) / 2.0
    denom = max_index - expected
    return jnp.where(
        denom == 0, 1.0, (sum_comb - expected) / denom
    ).astype(jnp.float32)


def mutual_info_score(labels_true, labels_pred) -> jax.Array:
    """Mutual information (nats) between two labelings
    (stats/mutual_info_score.cuh)."""
    c = contingency_matrix(labels_true, labels_pred).astype(jnp.float32)
    n = jnp.sum(c)
    pij = c / n
    pi = jnp.sum(pij, axis=1, keepdims=True)
    pj = jnp.sum(pij, axis=0, keepdims=True)
    terms = jnp.where(pij > 0, pij * jnp.log(pij / (pi * pj)), 0.0)
    return jnp.sum(terms).astype(jnp.float32)


def _cluster_entropy(counts) -> jax.Array:
    n = jnp.sum(counts)
    p = counts / n
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0))


def homogeneity_score(labels_true, labels_pred) -> jax.Array:
    """1 - H(C|K)/H(C) (stats/homogeneity_score.cuh)."""
    c = contingency_matrix(labels_true, labels_pred).astype(jnp.float32)
    h_c = _cluster_entropy(jnp.sum(c, axis=1))
    mi = mutual_info_score(labels_true, labels_pred)
    return jnp.where(h_c == 0, 1.0, mi / h_c).astype(jnp.float32)


def completeness_score(labels_true, labels_pred) -> jax.Array:
    """1 - H(K|C)/H(K) (stats/completeness_score.cuh)."""
    return homogeneity_score(labels_pred, labels_true)


def v_measure(labels_true, labels_pred, beta: float = 1.0) -> jax.Array:
    """Weighted harmonic mean of homogeneity and completeness
    (stats/v_measure.cuh)."""
    h = homogeneity_score(labels_true, labels_pred)
    c = completeness_score(labels_true, labels_pred)
    denom = beta * h + c
    return jnp.where(denom == 0, 0.0, (1 + beta) * h * c / denom)


def r2_score(y, y_hat) -> jax.Array:
    """Coefficient of determination (stats/r2_score.cuh)."""
    y = jnp.asarray(y, jnp.float32)
    y_hat = jnp.asarray(y_hat, jnp.float32)
    ss_res = jnp.sum((y - y_hat) ** 2)
    ss_tot = jnp.sum((y - jnp.mean(y)) ** 2)
    return 1.0 - ss_res / ss_tot


def regression_metrics(predictions, references):
    """(mean_abs_error, mean_squared_error, median_abs_error)
    (stats/regression_metrics.cuh)."""
    p = jnp.asarray(predictions, jnp.float32)
    r = jnp.asarray(references, jnp.float32)
    err = p - r
    return (
        jnp.mean(jnp.abs(err)),
        jnp.mean(err * err),
        jnp.median(jnp.abs(err)),
    )


def silhouette_score(
    x, labels, n_classes: int, metric: str = "sqeuclidean",
    tile_rows: int = 2048,
) -> jax.Array:
    """Mean silhouette coefficient (stats/silhouette_score.cuh).

    Tiled: for each row block, pairwise distances to the full dataset are
    reduced against the cluster one-hot matrix (one matmul) into per-cluster
    distance sums; a = own-cluster mean (self excluded), b = best
    other-cluster mean, s = (b - a) / max(a, b). Singleton clusters score 0
    (sklearn/reference convention).
    """
    x = jnp.asarray(x, jnp.float32)
    lab = jnp.asarray(labels).ravel()
    n = x.shape[0]
    onehot = (lab[:, None] == jnp.arange(n_classes)[None, :]).astype(jnp.float32)
    counts = jnp.sum(onehot, axis=0)  # (k,)

    scores = []
    for start in range(0, n, tile_rows):
        xb = x[start : start + tile_rows]
        lb = lab[start : start + tile_rows]
        d = dist_mod.pairwise_distance(xb, x, metric=metric)  # (b, n)
        csum = gemm(d, onehot)  # (b, k): per-cluster distance sums
        own = counts[lb]  # (b,)
        a = csum[jnp.arange(xb.shape[0]), lb] / jnp.maximum(own - 1, 1)
        other = jnp.where(
            (jnp.arange(n_classes)[None, :] == lb[:, None]) | (counts[None, :] == 0),
            jnp.inf,
            csum / jnp.maximum(counts[None, :], 1),
        )
        b = jnp.min(other, axis=1)
        s = jnp.where(own > 1, (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30), 0.0)
        scores.append(s)
    return jnp.mean(jnp.concatenate(scores))


def trustworthiness_score(
    x, x_embedded, n_neighbors: int, metric: str = "sqeuclidean",
    batch_size: int = 512,
) -> jax.Array:
    """How much the embedding preserves local structure
    (stats/trustworthiness_score.cuh): 1 - 2/(n*k*(2n-3k-1)) *
    sum over embedded-kNN intruders of (rank_in_original_space - k)."""
    x = jnp.asarray(x, jnp.float32)
    e = jnp.asarray(x_embedded, jnp.float32)
    n = x.shape[0]
    k = int(n_neighbors)
    penalty = jnp.float32(0.0)
    for start in range(0, n, batch_size):
        xb = x[start : start + batch_size]
        eb = e[start : start + batch_size]
        b = xb.shape[0]
        rows = jnp.arange(b)
        d_orig = dist_mod.pairwise_distance(xb, x, metric=metric)
        d_orig = d_orig.at[rows, start + rows].set(jnp.inf)  # exclude self
        # rank of every point in original space (0 = nearest)
        order = jnp.argsort(d_orig, axis=1)
        ranks = jnp.zeros_like(order).at[rows[:, None], order].set(
            jnp.arange(n, dtype=order.dtype)[None, :]
        )
        d_emb = dist_mod.pairwise_distance(eb, e, metric=metric)
        d_emb = d_emb.at[rows, start + rows].set(jnp.inf)
        _, knn_emb = jax.lax.top_k(-d_emb, k)
        r = ranks[rows[:, None], knn_emb]  # original ranks of embedded kNN
        penalty = penalty + jnp.sum(jnp.maximum(r - k + 1, 0).astype(jnp.float32))
    return 1.0 - penalty * (2.0 / (n * k * (2.0 * n - 3.0 * k - 1.0)))


def neighborhood_recall(
    indices, ref_indices,
    distances=None, ref_distances=None,
    eps: float = 0.001,
) -> jax.Array:
    """Recall of ANN results vs ground truth with eps-relative distance-tie
    matching (stats/detail/neighborhood_recall.cuh): a column matches if its
    id appears in the reference row, or (when distances are given) some
    reference distance is within relative eps."""
    idx = jnp.asarray(indices)
    ref = jnp.asarray(ref_indices)
    if idx.shape[0] != ref.shape[0]:
        raise ValueError("indices and ref_indices must have the same row count")
    if (distances is None) != (ref_distances is None):
        raise ValueError("distances and ref_distances must be provided together")
    match = jnp.any(idx[:, :, None] == ref[:, None, :], axis=2)
    if distances is not None:
        d = jnp.asarray(distances)[:, :, None]
        rd = jnp.asarray(ref_distances)[:, None, :]
        diff = jnp.abs(d - rd)
        m = jnp.maximum(jnp.abs(d), jnp.abs(rd))
        ratio = jnp.where(diff > eps, diff / jnp.maximum(m, 1e-30), diff)
        match = match | jnp.any(ratio <= eps, axis=2)
    return jnp.mean(match.astype(jnp.float32))
