"""Summary statistics (reference cpp/include/raft/stats/).

On TPU every reduction here is a single XLA-fused jnp expression; the design
work is (a) matching the reference's semantics exactly (sample vs population
variance, rowMajor axis conventions, weighted means) and (b) keeping the
key'd / masked variants matmul-shaped so they run on the MXU.

Reference headers: mean.cuh, sum.cuh, stddev.cuh, meanvar.cuh, mean_center.cuh,
cov.cuh, minmax.cuh, histogram.cuh, weighted_mean.cuh, dispersion.cuh,
entropy.cuh, kl_divergence.cuh, information_criterion.cuh.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.ops.linalg import gemm


def sum_(x, axis: int = 0) -> jax.Array:
    """Column (axis=0) / row (axis=1) sums (stats/sum.cuh)."""
    return jnp.sum(jnp.asarray(x), axis=axis)


def mean(x, axis: int = 0) -> jax.Array:
    """Column/row means (stats/mean.cuh)."""
    return jnp.mean(jnp.asarray(x), axis=axis)


def mean_center(x, mu=None, axis: int = 0) -> jax.Array:
    """Subtract per-column (axis=0) / per-row (axis=1) means
    (stats/mean_center.cuh)."""
    x = jnp.asarray(x)
    if mu is None:
        mu = jnp.mean(x, axis=axis)
    return x - jnp.expand_dims(mu, axis)


def mean_add(x, mu, axis: int = 0) -> jax.Array:
    """Inverse of :func:`mean_center` (stats/mean_center.cuh meanAdd)."""
    return jnp.asarray(x) + jnp.expand_dims(jnp.asarray(mu), axis)


def vars_(x, mu=None, sample: bool = True, axis: int = 0) -> jax.Array:
    """Per-column/row variance; ``sample`` selects the n-1 denominator
    (stats/stddev.cuh vars)."""
    x = jnp.asarray(x)
    n = x.shape[axis]
    if mu is None:
        mu = jnp.mean(x, axis=axis)
    d = x - jnp.expand_dims(mu, axis)
    denom = max(n - 1, 1) if sample else n
    return jnp.sum(d * d, axis=axis) / denom


def stddev(x, mu=None, sample: bool = True, axis: int = 0) -> jax.Array:
    """Per-column/row standard deviation (stats/stddev.cuh)."""
    return jnp.sqrt(vars_(x, mu, sample, axis))


def meanvar(x, sample: bool = True, axis: int = 0) -> Tuple[jax.Array, jax.Array]:
    """Mean and variance in one pass (stats/meanvar.cuh)."""
    x = jnp.asarray(x)
    mu = jnp.mean(x, axis=axis)
    return mu, vars_(x, mu, sample, axis)


def cov(x, mu=None, sample: bool = True, stable: bool = True) -> jax.Array:
    """Covariance matrix of row-sample data ``(n, d) -> (d, d)``
    (stats/cov.cuh). ``stable`` mean-centers first (the reference's non-stable
    path uses E[xy]-E[x]E[y]); the gemm accumulates in fp32 on the MXU."""
    x = jnp.asarray(x)
    n = x.shape[0]
    denom = max(n - 1, 1) if sample else n
    if mu is None:
        mu = jnp.mean(x, axis=0)
    if stable:
        xc = x - mu[None, :]
        return gemm(xc, xc, transpose_a=True) / denom
    exy = gemm(x, x, transpose_a=True) / denom
    return exy - jnp.outer(mu, mu) * (n / denom)


def minmax(x, axis: int = 0) -> Tuple[jax.Array, jax.Array]:
    """Per-column/row (min, max) (stats/minmax.cuh)."""
    x = jnp.asarray(x)
    return jnp.min(x, axis=axis), jnp.max(x, axis=axis)


def histogram(x, n_bins: int, lower: float, upper: float) -> jax.Array:
    """Per-column histograms over ``(n, d)`` data -> ``(n_bins, d)`` int32
    (stats/histogram.cuh). Fixed [lower, upper) range, equal-width bins,
    out-of-range samples are clamped into the edge bins (the reference's
    binner uses the same saturating convention). Computed as a one-hot
    matmul so the MXU does the scatter."""
    x = jnp.asarray(x)
    if x.ndim == 1:
        x = x[:, None]
    if n_bins <= 0:
        raise ValueError("n_bins must be positive")
    width = (upper - lower) / n_bins
    b = jnp.clip(((x - lower) / width).astype(jnp.int32), 0, n_bins - 1)
    onehot = (b[:, :, None] == jnp.arange(n_bins)[None, None, :]).astype(jnp.int32)
    return jnp.sum(onehot, axis=0).T  # (n_bins, d)


def weighted_mean(x, weights, axis: int = 0) -> jax.Array:
    """Weighted column (axis=0) / row (axis=1) means (stats/weighted_mean.cuh).
    ``weights`` has length ``x.shape[axis]`` and is normalized by its sum."""
    x = jnp.asarray(x)
    w = jnp.asarray(weights)
    if w.shape != (x.shape[axis],):
        raise ValueError(f"weights must be ({x.shape[axis]},), got {w.shape}")
    wsum = jnp.sum(w)
    return jnp.tensordot(w, x, axes=([0], [axis])) / wsum


def dispersion(
    centroids, cluster_sizes, global_centroid: Optional[jax.Array] = None
) -> jax.Array:
    """Cluster dispersion: sqrt(sum_i size_i * ||c_i - mu||^2) where mu is the
    size-weighted global centroid (stats/detail/dispersion.cuh:133)."""
    c = jnp.asarray(centroids, jnp.float32)
    sizes = jnp.asarray(cluster_sizes)
    n_points = jnp.sum(sizes)
    mu = (
        jnp.asarray(global_centroid)
        if global_centroid is not None
        else jnp.sum(c * sizes[:, None], axis=0) / jnp.maximum(n_points, 1)
    )
    d = c - mu[None, :]
    return jnp.sqrt(jnp.sum(jnp.sum(d * d, axis=1) * sizes))


def entropy(labels, n_classes: int) -> jax.Array:
    """Shannon entropy (nats) of an integer label distribution
    (stats/entropy.cuh)."""
    counts = jnp.bincount(jnp.asarray(labels).ravel(), length=n_classes)
    p = counts / jnp.maximum(jnp.sum(counts), 1)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0))


def kl_divergence(p, q) -> jax.Array:
    """KL(p || q) = sum p * log(p/q) over matched modeled/candidate
    distributions (stats/kl_divergence.cuh; terms with p<=0 contribute 0)."""
    p = jnp.asarray(p)
    q = jnp.asarray(q)
    return jnp.sum(jnp.where(p > 0, p * jnp.log(p / q), 0.0))


def information_criterion(
    log_likelihood, ic_type: str, n_params: int, n_samples: int
) -> jax.Array:
    """AIC / AICc / BIC from per-series log-likelihood
    (stats/detail/batched/information_criterion.cuh: ic = base - 2*loglike)."""
    ll = jnp.asarray(log_likelihood)
    n, t = float(n_params), float(n_samples)
    if ic_type == "aic":
        base = 2.0 * n
    elif ic_type == "aicc":
        base = 2.0 * (n + (n * (n + 1.0)) / (t - n - 1.0))
    elif ic_type == "bic":
        base = float(jnp.log(t)) * n
    else:
        raise ValueError(f"unknown ic_type {ic_type!r} (aic|aicc|bic)")
    return base - 2.0 * ll
