"""raft_tpu — TPU-native vector-search & ML-primitives framework.

A ground-up JAX/XLA/Pallas re-design of the capability surface of RAPIDS RAFT
(reference: cpp/include/raft/** at yinze00/raft v24.02): dense/sparse primitives,
clustering, ANN indexes (brute-force, IVF-Flat, IVF-PQ, CAGRA-style graph), and a
multi-chip distributed layer over XLA collectives.

Design principles (TPU-first, not a port):
  * static shapes everywhere — variable-length CUDA constructs (interleaved IVF
    lists, device hashmaps) become padded/bucketed dense layouts + validity masks;
  * matmul-dominant formulations so the MXU does the FLOPs (expanded distances,
    one-hot matmul gathers);
  * `jax.lax` control flow under jit; Pallas kernels for ops XLA won't fuse well;
  * multi-chip via `jax.sharding.Mesh` + `shard_map` collectives (psum/all_gather/
    ppermute) in place of NCCL/UCX (reference cpp/include/raft/comms/).
"""

__version__ = "0.1.0"

from raft_tpu.core.resources import Resources, current_resources, use_resources

from raft_tpu import (  # noqa: E402  (subpackage re-exports)
    cluster, comms, distributed, label, neighbors, obs, ops, random,
    resilience, solver, sparse, spectral, stats,
)

__all__ = [
    "cluster", "comms", "distributed", "label", "neighbors", "obs", "ops",
    "random", "resilience", "solver", "sparse", "spectral", "stats",
    "Resources",
    "current_resources",
    "use_resources",
    "__version__",
]
