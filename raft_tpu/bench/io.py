"""Real ANN dataset ingestion + ground-truth generation.

Reference surface: the raft-ann-bench dataset tooling —
``python/raft-ann-bench/src/raft-ann-bench/get_dataset`` (downloads
ann-benchmarks HDF5 and converts to fvecs/bin formats) and
``generate_groundtruth`` (exact kNN over the base set). This machine has no
network egress, so there is no downloader; the readers cover every on-disk
format those tools produce, and ``generate_groundtruth`` computes exact
truth with the in-repo brute force (batched, any metric).

Formats:
  * ``.fvecs`` / ``.ivecs`` / ``.bvecs`` — TEXMEX (sift/gist): each vector
    is an int32 dim header followed by dim payload items (f32/i32/u8).
  * ``.fbin`` / ``.u8bin`` / ``.i8bin`` / ``.ibin`` — big-ann-benchmarks:
    one (n, dim) int32 header, then n·dim payload items.
  * ``.hdf5`` — ann-benchmarks bundles: ``train`` / ``test`` /
    ``neighbors`` / ``distances`` datasets.

``load_real_dataset`` resolves a directory laid out like the TEXMEX
archives (``sift_base.fvecs`` + ``sift_query.fvecs`` +
``sift_groundtruth.ivecs``) or a single HDF5 bundle, so the headline bench
can run the real SIFT-1M when present and fall back to the synthetic
``siftlike`` otherwise.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import numpy as np

_VEC_PAYLOAD = {
    ".fvecs": (np.float32, 4),
    ".ivecs": (np.int32, 4),
    ".bvecs": (np.uint8, 1),
}

_BIN_PAYLOAD = {
    ".fbin": np.float32,
    ".u8bin": np.uint8,
    ".i8bin": np.int8,
    ".ibin": np.int32,
}


def read_vecs(path, count: Optional[int] = None) -> np.ndarray:
    """Read a TEXMEX .fvecs/.ivecs/.bvecs file → (n, dim) array."""
    ext = os.path.splitext(str(path))[1]
    if ext not in _VEC_PAYLOAD:
        raise ValueError(f"not a TEXMEX vecs file: {path}")
    dtype, itemsize = _VEC_PAYLOAD[ext]
    raw = np.fromfile(path, dtype=np.uint8)
    if raw.size < 4:
        raise ValueError(f"truncated vecs file: {path}")
    dim = int(np.frombuffer(raw[:4].tobytes(), np.int32)[0])
    if dim <= 0:
        raise ValueError(f"bad vecs dim {dim} in {path}")
    row_bytes = 4 + dim * itemsize
    n = raw.size // row_bytes
    if raw.size % row_bytes:
        raise ValueError(
            f"vecs file size {raw.size} not a multiple of row size "
            f"{row_bytes} (dim {dim}): {path}")
    if count is not None:
        n = min(n, int(count))
        raw = raw[: n * row_bytes]
    rows = raw.reshape(n, row_bytes)
    dims = rows[:, :4].copy().view(np.int32).reshape(-1)
    if not np.all(dims == dim):
        raise ValueError(f"inconsistent row dims in {path}")
    return rows[:, 4:].copy().view(dtype).reshape(n, dim)


def write_vecs(path, arr: np.ndarray) -> None:
    """Write (n, dim) → TEXMEX format (dtype chosen by extension).
    Atomic (core/fsio): a killed writer leaves no truncated dataset that a
    later bench run would trip over as a cryptic size-mismatch."""
    from raft_tpu.core.fsio import atomic_write

    ext = os.path.splitext(str(path))[1]
    dtype, _ = _VEC_PAYLOAD[ext]
    arr = np.ascontiguousarray(arr, dtype)
    n, dim = arr.shape
    hdr = np.full((n, 1), dim, np.int32)
    out = np.concatenate([hdr.view(np.uint8).reshape(n, 4),
                          arr.view(np.uint8).reshape(n, -1)], axis=1)
    with atomic_write(path) as f:
        out.tofile(f)


def read_bin(path, count: Optional[int] = None) -> np.ndarray:
    """Read a big-ann .fbin/.u8bin/.i8bin/.ibin file → (n, dim) array."""
    ext = os.path.splitext(str(path))[1]
    if ext not in _BIN_PAYLOAD:
        raise ValueError(f"not a big-ann bin file: {path}")
    dtype = _BIN_PAYLOAD[ext]
    with open(path, "rb") as f:
        n, dim = np.fromfile(f, np.int32, 2)
        n = int(n) if count is None else min(int(n), int(count))
        data = np.fromfile(f, dtype, n * int(dim))
    if data.size != n * int(dim):
        raise ValueError(f"truncated bin file: {path}")
    return data.reshape(n, int(dim))


def write_bin(path, arr: np.ndarray) -> None:
    """Atomic big-ann bin writer (same contract as :func:`write_vecs`)."""
    from raft_tpu.core.fsio import atomic_write

    ext = os.path.splitext(str(path))[1]
    arr = np.ascontiguousarray(arr, _BIN_PAYLOAD[ext])
    with atomic_write(path) as f:
        np.array(arr.shape, np.int32).tofile(f)
        arr.tofile(f)


def read_hdf5(path) -> Dict[str, np.ndarray]:
    """Read an ann-benchmarks HDF5 bundle → dict with ``train``/``test``
    and, when present, ``neighbors``/``distances``."""
    import h5py

    out = {}
    with h5py.File(path, "r") as f:
        for key in ("train", "test", "neighbors", "distances"):
            if key in f:
                out[key] = np.asarray(f[key])
    if "train" not in out or "test" not in out:
        raise ValueError(f"hdf5 bundle missing train/test datasets: {path}")
    return out


def read_any(path, count: Optional[int] = None) -> np.ndarray:
    """Dispatch on extension: TEXMEX vecs, big-ann bin, or .npy."""
    ext = os.path.splitext(str(path))[1]
    if ext in _VEC_PAYLOAD:
        return read_vecs(path, count)
    if ext in _BIN_PAYLOAD:
        return read_bin(path, count)
    if ext == ".npy":
        arr = np.load(path, mmap_mode="r")
        return np.asarray(arr[:count] if count else arr)
    raise ValueError(f"unknown dataset file format: {path}")


def generate_groundtruth(dataset, queries, k: int = 100,
                         metric: str = "sqeuclidean",
                         batch: int = 10_000) -> Tuple[np.ndarray, np.ndarray]:
    """Exact kNN ground truth (ids, distances) via the in-repo brute force —
    the generate_groundtruth tool analog. Batched over queries so the
    (q, n) distance block stays bounded."""
    import jax.numpy as jnp

    from raft_tpu.neighbors import brute_force

    index = brute_force.build(jnp.asarray(dataset, jnp.float32),
                              metric=metric)
    ids_out, d_out = [], []
    queries = np.asarray(queries, np.float32)
    for s in range(0, queries.shape[0], batch):
        v, i = brute_force.search(index, jnp.asarray(queries[s:s + batch]),
                                  k, select_algo="exact")
        ids_out.append(np.asarray(i))
        d_out.append(np.asarray(v))
    return np.concatenate(ids_out), np.concatenate(d_out)


def load_real_dataset(root, name: str = "sift",
                      max_rows: Optional[int] = None):
    """Resolve a real dataset directory → (base, queries, gt_ids | None).

    Accepts either a TEXMEX layout (``{name}_base.fvecs`` etc. under
    ``root/name`` or ``root``), a big-ann layout (``base.*bin`` +
    ``query.*bin`` + ``groundtruth.ibin``), or ``{name}.hdf5``. Returns
    None when nothing is found — callers fall back to synthetic data.
    """
    root = str(root)
    for d in (os.path.join(root, name), root):
        if not os.path.isdir(d):
            continue
        # TEXMEX layout
        for base_ext in (".fvecs", ".bvecs"):
            base_p = os.path.join(d, f"{name}_base{base_ext}")
            if os.path.exists(base_p):
                qp = next((p for p in (
                    os.path.join(d, f"{name}_query.fvecs"),
                    os.path.join(d, f"{name}_query.bvecs"))
                    if os.path.exists(p)), None)
                if qp is None:
                    continue
                base = read_vecs(base_p, max_rows)
                gt_p = os.path.join(d, f"{name}_groundtruth.ivecs")
                # shipped ground truth is over the FULL base: invalid once
                # max_rows truncates (ids could point past the rows
                # returned) — callers regenerate via generate_groundtruth
                gt = (read_vecs(gt_p)
                      if os.path.exists(gt_p) and max_rows is None else None)
                return (base, read_vecs(qp), gt)
        # big-ann layout
        for base_ext in _BIN_PAYLOAD:
            base_p = os.path.join(d, f"base{base_ext}")
            if os.path.exists(base_p):
                qp = next((os.path.join(d, f"query{e}")
                           for e in _BIN_PAYLOAD
                           if os.path.exists(os.path.join(d, f"query{e}"))),
                          None)
                if qp is None:
                    continue
                gt_p = os.path.join(d, "groundtruth.ibin")
                gt = (read_bin(gt_p)
                      if os.path.exists(gt_p) and max_rows is None else None)
                return (read_bin(base_p, max_rows), read_bin(qp), gt)
    # single-file HDF5 bundle
    for p in (os.path.join(root, f"{name}.hdf5"),
              os.path.join(root, name, f"{name}.hdf5")):
        if os.path.exists(p):
            z = read_hdf5(p)
            base = z["train"][:max_rows] if max_rows else z["train"]
            gt = None if max_rows else z.get("neighbors")
            return base, z["test"], gt
    return None
