"""Checkpointed bench runs: heartbeat JSONL side-channel + salvage.

Round 5's wedge (``BENCH_r05.json`` rc=124, tail="", parsed=null) proved that
a bench run which dies mid-suite leaves NOTHING — every finished section's
result lived only in the child's memory. This module is the fix: the
measurement child appends one JSONL record to a side-channel file the moment
each suite section completes (plus a periodic heartbeat line so a wedge is
distinguishable from slow progress), and :func:`salvage` reconstructs the
best-available headline metric line from whatever checkpoints survived a kill.

Record types (one JSON object per line; every record carries ``t`` epoch
seconds and ``elapsed_s`` since the writer started):

* ``run_start``  — platform + the suite config (n/dim/q/k/dataset)
* ``section``    — ``name`` + ``data`` (the section's extras dict), written
  the moment the section finishes
* ``heartbeat``  — periodic pulse with the in-progress ``section`` name
* ``run_end``    — final headline metric (present only on clean completion)

Import-light on purpose (stdlib only): bench.py's jax-free orchestrator reads
the file after a failed run, and ``scripts/bench_salvage.py`` is a thin CLI
over :func:`read_progress` + :func:`salvage`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

ENV_VAR = "RAFT_TPU_BENCH_HEARTBEAT"
DEFAULT_PATH = os.path.join("results", "bench_progress.jsonl")


def process_info() -> tuple:
    """(process_index, process_count) for stamping records — the stdlib-only
    twin of obs/tracing.process_info (this module must stay importable by
    file path in jax-free parents, so it cannot share code with the obs
    package). Same contract: env override first, then an ALREADY-initialized
    jax backend (never triggers backend init — that is the wedge class this
    whole module guards against), else (0, 1)."""
    import sys as _sys

    pi = os.environ.get("RAFT_TPU_PROCESS_INDEX", "").strip()
    pc = os.environ.get("RAFT_TPU_PROCESS_COUNT", "").strip()
    if pi.lstrip("-").isdigit():
        return int(pi), int(pc) if pc.lstrip("-").isdigit() else 1
    try:
        jax = _sys.modules.get("jax")
        xb = _sys.modules.get("jax._src.xla_bridge")
        if jax is not None and xb is not None and \
                getattr(xb, "_backends", None):
            return int(jax.process_index()), int(jax.process_count())
    # a stamp is best-effort decoration on a crash-safety path: any jax
    # internals mismatch must degrade to (0, 1), never block a checkpoint
    except Exception:  # graftlint: ignore[swallowed-exception]
        pass
    return 0, 1

# single home of the headline denominator (bench.py reads it from here so a
# retune cannot diverge between live and salvaged lines)
NORTH_STAR_QPS = 1e6

# salvage headline preference: same order bench.py would pick its headline
_HEADLINE_ORDER = ("ivf_pq", "ivf_flat", "brute_force", "cagra")


class ProgressWriter:
    """Crash-safe appender: every record is written, flushed and fsync'd in
    one call, so a SIGKILL between sections loses at most the in-flight
    line. A daemon pulse thread emits heartbeats every ``pulse_interval_s``.
    """

    def __init__(self, path: str, platform: str = "",
                 pulse_interval_s: float = 15.0):
        self.path = path
        self._platform = platform
        self._interval = pulse_interval_s
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._section = ""
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def _write(self, rec: dict) -> None:
        pi, pc = process_info()
        rec = {
            "t": round(time.time(), 3),
            "elapsed_s": round(time.monotonic() - self._t0, 3),
            "process_index": pi,
            "process_count": pc,
            **rec,
        }
        line = json.dumps(rec)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())

    def start(self, config: Optional[dict] = None) -> None:
        self._write({"type": "run_start", "platform": self._platform,
                     "config": config or {}})
        self._thread = threading.Thread(target=self._pulse, daemon=True)
        self._thread.start()

    def set_section(self, name: str) -> None:
        """Mark ``name`` as in progress (heartbeat lines carry it, so a
        post-mortem shows WHERE the run wedged)."""
        self._section = name

    def section(self, name: str, data: dict) -> None:
        """Checkpoint one completed suite section."""
        self._section = ""
        self._write({"type": "section", "name": name,
                     "platform": self._platform, "data": data})

    def finish(self, result: Optional[dict] = None) -> None:
        self._stop.set()
        self._write({"type": "run_end", "platform": self._platform,
                     "result": result or {}})

    def _pulse(self) -> None:
        while not self._stop.wait(self._interval):
            self._write({"type": "heartbeat", "section": self._section})


class NullProgress:
    """No-op writer (heartbeat channel not configured)."""

    path = ""

    def start(self, config=None):
        pass

    def set_section(self, name):
        pass

    def section(self, name, data):
        pass

    def finish(self, result=None):
        pass


def truncate(path: str) -> None:
    """Start a fresh heartbeat file for a new run (the orchestrator's
    per-run reset). Lives here so every touch of the side-channel file —
    create, append, reset — goes through this module's contract."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w"):
        pass


def truncate_dir(directory: str, suffix: str = ".jsonl",
                 prefix: str = "") -> None:
    """Per-attempt reset of telemetry artifacts: remove stale per-process
    files so a fleet merge (or a Perfetto session) never folds in a dead
    attempt's output. ``prefix`` scopes the sweep when the directory also
    holds unrelated files (results/ keeps committed round artifacts)."""
    try:
        names = os.listdir(directory)
    except OSError:
        return
    for name in names:
        # the + ".tmp" arm sweeps write_artifact temp files a SIGKILL
        # stranded mid-write (os.replace never ran)
        if (name.endswith(suffix) or name.endswith(suffix + ".tmp")) and \
                name.startswith(prefix):
            try:
                os.remove(os.path.join(directory, name))
            except OSError:
                pass


def write_artifact(path: str, doc: dict) -> None:
    """Crash-safely write one JSON artifact — tmp file, flush, fsync, then
    atomic ``os.replace`` — the sanctioned channel for bench-side trace
    exports and fleet views: a kill mid-write leaves either the old file or
    the complete new one, never a torn one (graftlint's span-name rule
    points direct exports here)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def export_metrics(path: str, snapshot: dict,
                   extra: Optional[dict] = None) -> dict:
    """Append one process-stamped metrics snapshot line to ``path`` with the
    heartbeat file's durability (flush + fsync per record) — the bench-side
    analog of ``obs.export_jsonl`` (which flushes but does not fsync, and
    which bench code must not call directly). Returns the record written."""
    pi, pc = process_info()
    rec = {"t": round(time.time(), 3), "process_index": pi,
           "process_count": pc, **(extra or {}), **snapshot}
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return rec


def from_env(platform: str = ""):
    """The measurement child's entry: a real writer when the orchestrator
    exported ``RAFT_TPU_BENCH_HEARTBEAT``, else a no-op."""
    path = os.environ.get(ENV_VAR, "").strip()
    if not path:
        return NullProgress()
    return ProgressWriter(path, platform=platform)


def read_progress(path: str) -> List[dict]:
    """Parse a heartbeat file, skipping any torn/corrupt trailing line."""
    records = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    except OSError:
        return []
    return records


def _shape_tag(config: dict) -> str:
    """The metric shape tag, built EXACTLY the way bench.py's run_suite
    builds it (``{ds}{n//1000}k_{dim}d_k{k}``) so a salvaged line lands in
    the same metric series as a live run of the identical config."""
    ds = str(config.get("dataset", "unknown"))
    n, dim, k = config.get("n"), config.get("dim"), config.get("k")
    if all(isinstance(v, int) and v > 0 for v in (n, dim, k)):
        base = "sift" if ds == "sift-real" else "siftlike"
        return f"{base}{n // 1000}k_{dim}d_k{k}"
    return ds


def _salvage_segment(segment: List[dict], source: str) -> Optional[dict]:
    """Salvage one run's records (run_start..next run_start); None when no
    section with a positive QPS exists. Last checkpoint per section wins."""
    config: dict = {}
    if segment and segment[0].get("type") == "run_start":
        config = segment[0].get("config") or {}
    sections: dict = {}
    platform = ""
    for rec in segment:
        if rec.get("type") == "section" and isinstance(rec.get("data"), dict):
            sections[rec.get("name")] = rec["data"]
            platform = rec.get("platform") or platform

    for name in _HEADLINE_ORDER:
        data = sections.get(name)
        if not isinstance(data, dict):
            continue
        qps = data.get("qps")
        if isinstance(qps, (int, float)) and qps > 0:
            break
    else:
        return None

    recall = data.get("recall")
    metric = f"{name}_qps_{_shape_tag(config)}"
    # recall suffix parity with run_suite: ivf headlines carry it, the
    # brute-force anchor does not
    if name != "brute_force" and isinstance(recall, (int, float)):
        metric += f"_recall{recall}"
    out = {
        "metric": metric,
        "value": float(qps),
        "unit": "QPS",
        "vs_baseline": round(float(qps) / NORTH_STAR_QPS, 4),
        "salvaged": True,
        "platform": platform,
        "note": "reconstructed from bench_progress.jsonl checkpoints "
                "(run died mid-suite)",
        "extras": {"config": config, **sections},
    }
    if isinstance(recall, (int, float)):
        out["recall_gate_met"] = bool(recall >= 0.95)
    if source:
        out["salvaged_from"] = source
    return out


def salvage(records: List[dict], source: str = "") -> Optional[dict]:
    """Reconstruct the best-available headline metric line from checkpoint
    records (tagged ``"salvaged": true``), or None when no section with a
    positive QPS survived anywhere in the file.

    Runs are separated by ``run_start`` records (a progress file may hold a
    failed TPU attempt followed by a CPU retry); the NEWEST run with a
    salvageable section wins, but a retry that died before its first
    checkpoint falls back to the previous attempt's sections rather than
    discarding them.
    """
    bounds = [i for i, r in enumerate(records)
              if r.get("type") == "run_start"]
    if not bounds or bounds[0] != 0:
        bounds.insert(0, 0)  # leading checkpoint(s) with no run_start marker
    bounds.append(len(records))
    for si in range(len(bounds) - 2, -1, -1):
        line = _salvage_segment(records[bounds[si]:bounds[si + 1]], source)
        if line is not None:
            return line
    return None
