"""ANN benchmark harness (reference python/raft-ann-bench + cpp/bench/ann):
config-driven build/search sweeps reporting QPS, recall, and build time."""

from raft_tpu.bench.runner import run_benchmark

__all__ = ["run_benchmark"]
