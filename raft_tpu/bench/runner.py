"""Config-driven ANN benchmark runner (reference raft-ann-bench:
per-algorithm param sweeps producing QPS/recall records,
docs/source/raft_ann_benchmarks.md:420-438; JSON configs like
cpp/bench/ann/src/common/conf.hpp).

Usage:
    python -m raft_tpu.bench.runner config.json -o results.json

Config schema (JSON / dict):
    {
      "dataset": {"kind": "blobs", "n": 100000, "dim": 64, "n_queries": 1000,
                  "n_clusters": 512, "seed": 0}
               | {"kind": "files", "base": "base.npy", "queries": "q.npy"},
      "k": 10,
      "algos": [
        {"name": "brute_force", "build": {}, "search": [{}]},
        {"name": "ivf_flat", "build": {"n_lists": 256},
         "search": [{"n_probes": 8}, {"n_probes": 32}]},
        {"name": "ivf_pq", "build": {"n_lists": 256, "pq_dim": 32},
         "search": [{"n_probes": 32, "refine_ratio": 4}]},
        {"name": "cagra", "build": {"graph_degree": 32},
         "search": [{"max_iterations": 24}]}
      ]
    }

Each (algo, search-params) pair yields one record:
    {"algo", "build_params", "search_params", "build_s", "qps", "recall"}
— the reference harness's Latency/QPS/Recall counters.
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import random as rt_random
from raft_tpu import stats
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq, refine


def _force(x):
    return float(jnp.sum(jnp.where(jnp.isfinite(x), x, 0)))


def _load_dataset(spec: Dict):
    kind = spec.get("kind", "blobs")
    if kind == "files":
        # any supported on-disk format: .npy, TEXMEX .fvecs/.bvecs,
        # big-ann .fbin/.u8bin/... (bench/io.py readers)
        from raft_tpu.bench.io import read_any

        base = read_any(spec["base"], spec.get("max_rows"))
        queries = read_any(spec["queries"])
        return (jnp.asarray(np.asarray(base, np.float32)),
                jnp.asarray(queries, jnp.float32))
    if kind == "real":
        # resolve a standard dataset directory (TEXMEX / big-ann / hdf5);
        # errors out rather than silently benching synthetic data
        from raft_tpu.bench.datasets import data_dir
        from raft_tpu.bench.io import load_real_dataset

        found = load_real_dataset(
            spec.get("root") or data_dir(),
            spec.get("name", "sift"), spec.get("max_rows"))
        if found is None:
            raise FileNotFoundError(
                f"real dataset {spec.get('name', 'sift')!r} not found")
        base, queries, _ = found
        return (jnp.asarray(np.asarray(base, np.float32)),
                jnp.asarray(np.asarray(queries, np.float32)))
    if kind == "blobs":
        n, dim = int(spec["n"]), int(spec["dim"])
        q = int(spec.get("n_queries", 1000))
        data, _, _ = rt_random.make_blobs(
            int(spec.get("seed", 0)), n + q, dim,
            n_clusters=int(spec.get("n_clusters", 1024)),
            cluster_std=float(spec.get("cluster_std", 1.0)),
            center_box=(-8.0, 8.0),
        )
        return data[:n], data[n:]
    if kind == "siftlike":
        from raft_tpu.bench.datasets import sift_like

        data, queries = sift_like(
            int(spec["n"]), int(spec.get("dim", 128)),
            int(spec.get("n_queries", 10_000)), int(spec.get("seed", 0)))
        return (jnp.asarray(data, jnp.float32),
                jnp.asarray(queries, jnp.float32))
    raise ValueError(f"unknown dataset kind {kind!r}")


def _timed_qps(run, queries, reps: int) -> float:
    v, _ = run(queries)
    _force(v)
    t0 = time.perf_counter()
    for _ in range(reps):
        v, _ = run(queries)
    _force(v)
    return queries.shape[0] / ((time.perf_counter() - t0) / reps)


def _make_algo(name: str, build_params: Dict, dataset, k: int, metric: str):
    """Returns (build_fn() -> state, search_fn(state, sp, queries) -> (v, i)).

    ``metric`` (the config-level key) flows into every build unless the
    algo's own build params override it — recall vs ground truth is only
    meaningful when both rank under the same metric. Mutates ``build_params``
    in place so records report the metric actually used."""
    if name != "cagra":  # cagra build is metric-free (graph construction)
        build_params.setdefault("metric", metric)
    if name == "brute_force":
        return (lambda: brute_force.build(dataset, **build_params),
                lambda ix, sp, qs: brute_force.search(ix, qs, k, **sp))
    if name == "ivf_flat":
        return (lambda: ivf_flat.build(dataset, ivf_flat.IvfFlatParams(**build_params)),
                lambda ix, sp, qs: ivf_flat.search(ix, qs, k, **sp))
    if name == "ivf_pq":
        def search_pq(ix, sp, qs):
            sp = dict(sp)
            ratio = int(sp.pop("refine_ratio", 1))
            if ratio > 1:
                _, cand = ivf_pq.search(ix, qs, k * ratio, **sp)
                return refine.refine(dataset, qs, cand, k,
                                     metric=build_params["metric"])
            return ivf_pq.search(ix, qs, k, **sp)

        return (lambda: ivf_pq.build(dataset, ivf_pq.IvfPqParams(**build_params)),
                search_pq)
    if name == "cagra":
        def search_cagra(ix, sp, qs):
            return cagra.search(ix, qs, k, cagra.CagraSearchParams(**sp))

        return (lambda: cagra.build(dataset, cagra.CagraParams(**build_params)),
                search_cagra)
    raise ValueError(f"unknown algo {name!r}")


def run_benchmark(config: Dict, reps: int = 3) -> List[Dict]:
    """Run every (algo, search-params) combination; returns records sorted
    by algo then recall (the QPS@recall curve)."""
    dataset, queries = _load_dataset(config["dataset"])
    k = int(config.get("k", 10))
    metric = config.get("metric", "sqeuclidean")

    gt_v, gt_i = brute_force.search(
        brute_force.build(dataset, metric=metric),
        queries, k, select_algo="exact",
    )
    _force(gt_v)

    records = []
    for algo in config["algos"]:
        name = algo["name"]
        build_params = dict(algo.get("build", {}))
        if name == "cagra" and metric != "sqeuclidean":
            raise ValueError("cagra bench entries support sqeuclidean only")
        build_fn, search_fn = _make_algo(name, build_params, dataset, k, metric)
        t0 = time.perf_counter()
        state = build_fn()
        jax.block_until_ready(state)  # full-pytree barrier for build timing
        build_s = time.perf_counter() - t0

        for sp in algo.get("search", [{}]):
            v, i = search_fn(state, sp, queries)
            recall = float(stats.neighborhood_recall(i, gt_i, v, gt_v))
            qps = _timed_qps(lambda qs: search_fn(state, sp, qs), queries, reps)
            records.append({
                "algo": name,
                "build_params": build_params,
                "search_params": sp,
                "build_s": round(build_s, 2),
                "qps": round(qps, 1),
                "recall": round(recall, 4),
                "k": k,
            })
    records.sort(key=lambda r: (r["algo"], r["recall"]))
    return records



def main(argv=None):
    from raft_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("config", help="JSON config path")
    ap.add_argument("-o", "--output", default=None, help="results JSON path")
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args(argv)
    with open(args.config) as f:
        config = json.load(f)
    records = run_benchmark(config, reps=args.reps)
    text = json.dumps(records, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text + "\n")
    print(text)


if __name__ == "__main__":
    main()
