"""Benchmark datasets.

The reference harness benches on sift-128-euclidean / deep-image-96 /
big-ann subsets (docs/source/raft_ann_benchmarks.md:282-300). This machine
has no network egress, so the harness uses a generate-once-and-cache
synthetic with SIFT-like statistics instead of interpolated blobs (round-2
VERDICT Weak#8: 4096 well-separated gaussian blobs flatter IVF — recall@
nprobe was not comparable to published sift numbers):

  * two-level mixture — Zipf-weighted coarse clusters with per-cluster
    anisotropy, so coarse cells overlap and cluster populations are skewed
    like real descriptor data;
  * correlated dimensions via a shared low-rank mixing matrix with a
    decaying spectrum (SIFT dims are strongly correlated);
  * non-negative uint8 marginals (SIFT is a clipped uint8 histogram).

The result is labeled honestly as `siftlike` in metric names — it is NOT
the real SIFT-1M, but its recall-vs-nprobe curves sit in the same regime
(verified against the blobs generator: siftlike needs ~2× the probes for
the same recall@10).
"""

from __future__ import annotations

import os

import numpy as np


def data_dir() -> str:
    """The dataset root: ``RAFT_TPU_DATA_DIR``, default
    ``~/.cache/raft_tpu_data`` — the ONE registered default every
    consumer (bench real-data loaders, cached synthetic sets) resolves
    through (env-knob drift gate)."""
    return os.environ.get(
        "RAFT_TPU_DATA_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "raft_tpu_data"),
    )


def _cache_dir() -> str:
    d = data_dir()
    os.makedirs(d, exist_ok=True)
    return d


def sift_like(n: int, dim: int = 128, n_queries: int = 10_000,
              seed: int = 0):
    """(dataset uint8 (n, dim), queries uint8 (n_queries, dim)), cached on
    disk after the first call."""
    path = os.path.join(_cache_dir(),
                        f"siftlike_{n}_{dim}_{n_queries}_{seed}.npz")
    if os.path.exists(path):
        z = np.load(path)
        return z["data"], z["queries"]

    rng = np.random.default_rng(seed)
    n_coarse = max(64, min(4096, n // 256))
    total = n + n_queries

    # Zipf-ish coarse weights: a few dense regions, a long tail
    w = 1.0 / np.arange(1, n_coarse + 1) ** 0.7
    w /= w.sum()
    assign = rng.choice(n_coarse, total, p=w)

    centers = rng.standard_normal((n_coarse, dim)).astype(np.float32) * 2.0
    # per-cluster anisotropic spread (clusters overlap unevenly)
    spread = (0.5 + rng.random((n_coarse, dim)) * 1.5).astype(np.float32)

    x = centers[assign] + rng.standard_normal((total, dim)).astype(np.float32) \
        * spread[assign]

    # correlated dims: mix through a random basis with a decaying spectrum
    basis = np.linalg.qr(rng.standard_normal((dim, dim)))[0].astype(np.float32)
    spectrum = (1.0 / np.sqrt(1.0 + np.arange(dim) / 8.0)).astype(np.float32)
    x = x @ (basis * spectrum[None, :])

    # non-negative uint8 marginals, SIFT-style (half-wave rectified + clip)
    x = np.maximum(x, 0.0)
    scale = 110.0 / max(np.percentile(x, 99.5), 1e-6)
    x = np.clip(x * scale, 0, 255).astype(np.uint8)

    data, queries = x[:n], x[n:]
    np.savez(path, data=data, queries=queries)
    return data, queries


def deep_like_rows(row_ids, dim: int = 96, seed: int = 0,
                   n_coarse: int = 4096):
    """Row-ADDRESSABLE DEEP-shaped generator: row r is a pure function of
    ``(seed, r)`` (counter-based PRNG), so 100M-row benches can stream
    build chunks and later regenerate exactly the candidate rows needed
    for exact re-ranking — the raw (n, dim) matrix never exists anywhere.

    Same two-level-mixture character as :func:`sift_like` (Zipf-weighted
    overlapping clusters), but fp32 L2-normalized like the DEEP descriptors
    (big-ann deep-96). Runs on device; jit/vmap-safe.
    """
    import jax
    import jax.numpy as jnp

    row_ids = jnp.asarray(row_ids, jnp.int32)
    key = jax.random.key(seed)
    # centers/spread ride a fold_in index no row id can collide with:
    # row ids are int32 (≤ 0x7FFFFFFF), this is above that range but still
    # uint32-representable as fold_in requires
    kc, ks = jax.random.split(jax.random.fold_in(key, 0x80000001))
    centers = jax.random.normal(kc, (n_coarse, dim), jnp.float32) * 2.0
    spread = 0.5 + 1.5 * jax.random.uniform(ks, (n_coarse,), jnp.float32)
    w = 1.0 / jnp.arange(1, n_coarse + 1, dtype=jnp.float32) ** 0.7
    cw = jnp.cumsum(w / jnp.sum(w))

    def one(r):
        kr = jax.random.fold_in(key, r)
        ku, kn = jax.random.split(kr)
        c = jnp.searchsorted(cw, jax.random.uniform(ku))
        c = jnp.minimum(c, n_coarse - 1)
        return centers[c] + jax.random.normal(kn, (dim,)) * spread[c]

    rows = jax.vmap(one)(row_ids.reshape(-1))
    rows = rows / jnp.maximum(
        jnp.linalg.norm(rows, axis=-1, keepdims=True), 1e-30)
    return rows.reshape(row_ids.shape + (dim,))
