"""Sharded exact kNN: dataset row-sharded over the mesh, cross-shard merge.

The MNMG pattern the reference teaches for brute-force search
(docs/source/using_raft_comms.rst; knn_merge_parts.cuh:140 is the single-GPU
merge primitive): every rank scans its local shard, produces a local top-k,
then ranks exchange candidate lists and re-select — here one
``all_gather`` over the mesh axis followed by an exact ``select_k`` on the
(world * k)-wide candidate matrix, all inside a single ``shard_map`` so XLA
schedules the local gemm and the ICI all-gather as one program.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import Comms, make_comms, shard_padded
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.compat import shard_map
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.trace import traced
from raft_tpu.neighbors.brute_force import _MAX_METRICS, _tile_distances
from raft_tpu.ops import distance as dist_mod
from raft_tpu.ops.select_k import select_k


@dataclass
class ShardedBruteForceIndex:
    """Row-sharded exact-search index. ``dataset`` is padded to a multiple of
    the communicator size and placed with a row sharding over the mesh axis;
    ``n_total`` is the true (unpadded) row count."""

    dataset: jax.Array  # (n_padded, dim), sharded P(axis, None)
    norms: Optional[jax.Array]  # (n_padded,), sharded P(axis)
    metric: str
    metric_arg: float
    n_total: int
    comms: Comms

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def size(self) -> int:
        return self.n_total


@traced("distributed.brute_force::build")
def build(
    dataset,
    metric: str = "sqeuclidean",
    metric_arg: float = 2.0,
    comms: Optional[Comms] = None,
    res: Optional[Resources] = None,
) -> ShardedBruteForceIndex:
    """Shard the dataset row-wise over the communicator and precompute norms.

    (brute_force-inl.cuh:337 per rank; the sharding is the distribution step
    raft leaves to Dask.)
    """
    res = res or current_resources()
    comms = comms or make_comms(res)
    metric = dist_mod.canonical_metric(metric)
    dataset = jnp.asarray(dataset)
    n = dataset.shape[0]
    dataset, _ = shard_padded(dataset, comms)
    norms = None
    if metric in ("sqeuclidean", "euclidean", "cosine"):
        norms = dist_mod.sqnorm(dataset)  # computed shard-local by XLA
    return ShardedBruteForceIndex(dataset, norms, metric, metric_arg, n, comms)


@functools.lru_cache(maxsize=64)
def _make_search_fn(mesh, axis, metric, metric_arg, k, n_total, select_algo,
                    has_filter, has_norms, compute_dtype, world=0):
    select_min = metric not in _MAX_METRICS
    bad = jnp.float32(jnp.inf if select_min else -jnp.inf)
    needs_norms = metric in ("sqeuclidean", "euclidean", "cosine")

    def body(shard, shard_norms, queries, filter_words, ok):
        rows = shard.shape[0]
        rank = jax.lax.axis_index(axis)
        gids = rank * rows + jnp.arange(rows, dtype=jnp.int32)
        qn = dist_mod.sqnorm(queries) if needs_norms else None
        tn = shard_norms if has_norms else jnp.zeros((rows,), jnp.float32)
        d = _tile_distances(
            queries, qn, shard, tn, metric, metric_arg, compute_dtype
        )
        valid = gids < n_total
        if has_filter:
            valid = valid & Bitset(filter_words, n_total).test(gids)
        d = jnp.where(valid[None, :], d, bad)
        if k > rows:
            # k exceeds this shard's row count (legal: k is validated against
            # the GLOBAL n); pad local candidates so select_k stays in range
            d = jnp.pad(d, ((0, 0), (0, k - rows)), constant_values=bad)
            gids = jnp.pad(gids, (0, k - rows), constant_values=-1)
        vals, sel = select_k(d, k, select_min=select_min, algo=select_algo)
        ids = jnp.where(vals == bad, -1, jnp.take(gids, sel))
        # degraded mode: a dead shard's candidates are blanked before the
        # merge, so the partial merge is exact over the survivors
        alive = ok[0, 0] > 0
        vals = jnp.where(alive, vals, bad)
        ids = jnp.where(alive, ids, -1)
        # cross-shard butterfly merge (knn_merge_parts analog; per-link
        # bytes k·log2(world) — see _sharding.merge_shards)
        from raft_tpu.distributed._sharding import merge_shards

        return merge_shards(vals, ids, k, axis, world, select_min)

    nspec = P(axis) if has_norms else P()
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis, None), nspec, P(), P(), P(axis, None)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


@traced("distributed.brute_force::search")
def search(
    index: ShardedBruteForceIndex,
    queries,
    k: int,
    filter: Optional[Bitset] = None,
    select_algo: str = "exact",
    res: Optional[Resources] = None,
    health=None,
) -> Tuple[jax.Array, jax.Array]:
    """Sharded exact kNN: (distances (q, k), global indices (q, k)),
    replicated on every mesh slot, as a
    :class:`~raft_tpu.distributed._sharding.SearchResult` (carries
    ``coverage``/``degraded`` when shards were dropped from the merge)."""
    res = res or current_resources()
    queries = jnp.asarray(queries)
    if queries.shape[1] != index.dim:
        raise ValueError(f"query dim {queries.shape[1]} != index dim {index.dim}")
    if not 0 < k <= index.n_total:
        raise ValueError(f"k={k} out of range for n={index.n_total}")
    if filter is not None and filter.n_bits != index.n_total:
        raise ValueError(
            f"filter covers {filter.n_bits} bits but index has {index.n_total} rows"
        )
    comms = index.comms
    fn = _make_search_fn(
        comms.mesh,
        comms.axis,
        index.metric,
        float(index.metric_arg),
        int(k),
        index.n_total,
        select_algo,
        filter is not None,
        index.norms is not None,
        res.compute_dtype if index.metric in dist_mod.EXPANDED_METRICS else None,
        comms.size,
    )
    fwords = filter.bits if filter is not None else jnp.zeros((1,), jnp.uint32)
    norms = (
        index.norms
        if index.norms is not None
        else jnp.zeros((index.dataset.shape[0],), jnp.float32)
    )
    from raft_tpu.distributed._sharding import (SearchResult, probe_shards,
                                                shard_ok_device)

    report = probe_shards("brute_force", comms.size, index.n_total,
                          health=health)
    ok_dev = shard_ok_device(report.ok, comms)
    vals, ids = fn(index.dataset, norms, queries, fwords, ok_dev)
    return SearchResult(vals, ids, coverage=report.coverage,
                        degraded=report.degraded,
                        lost_shards=report.dropped)
