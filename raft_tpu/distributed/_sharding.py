"""Shared shard-preparation helpers for the distributed IVF indexes.

One implementation of the row-sharding, SPMD assign+spill phase, padded
list sizing, local dense fallback scan, cross-shard merge, and the
degraded-mode dispatch gate (:func:`probe_shards` + :class:`SearchResult`)
— ivf_flat and ivf_pq compose these (round-3 review: the two modules had
begun to drift apart with four copies of this logic); brute_force and
cagra share the availability pieces."""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from raft_tpu import obs, resilience
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.core.compat import shard_map
from raft_tpu.core.interruptible import InterruptedException
from raft_tpu.neighbors import _packing
from raft_tpu.ops.select_k import select_k
from raft_tpu.resilience.retry import record_event


# ---------------------------------------------------------------------------
# Degraded-mode dispatch: shard probe, coverage accounting, result carrier
# ---------------------------------------------------------------------------


class SearchResult(tuple):
    """A ``(distances, indices)`` pair with availability metadata riding
    along. Unpacks exactly like the plain 2-tuple every caller already
    writes (``vals, ids = search(...)``); degraded-mode consumers read the
    attributes:

    * ``coverage`` — fraction of index rows held by the shards whose
      candidates entered the top-k merge (1.0 on the healthy path).
    * ``degraded`` — True when any shard's candidates were dropped.
    * ``lost_shards`` — the shard ranks dropped from this dispatch.
    """

    def __new__(cls, distances, indices, coverage: float = 1.0,
                degraded: bool = False, lost_shards: Tuple[int, ...] = ()):
        self = tuple.__new__(cls, (distances, indices))
        self.coverage = float(coverage)
        self.degraded = bool(degraded)
        self.lost_shards = tuple(int(s) for s in lost_shards)
        return self

    @property
    def distances(self):
        return self[0]

    @property
    def indices(self):
        return self[1]


@dataclass(frozen=True)
class ShardReport:
    """One dispatch's availability verdict (:func:`probe_shards`)."""

    ok: np.ndarray            # (world,) bool — shards serving this dispatch
    coverage: float           # fraction of rows the serving shards hold
    degraded: bool
    dropped: Tuple[int, ...]  # shard ranks excluded from this dispatch


def shard_rows_held(world: int, n_total: int):
    """Real (unpadded) rows per shard under the one row-partitioning
    convention every distributed index uses: ``rows_per = ceil(n/world)``
    contiguous rows per shard, short tail on the last."""
    rows_per = -(-int(n_total) // int(world))
    return [max(0, min(rows_per, int(n_total) - r * rows_per))
            for r in range(int(world))]


def probe_shards(algo: str, world: int, n_total: int,
                 health: Optional[resilience.ShardHealth] = None,
                 phase: str = "search") -> ShardReport:
    """Host-side per-shard dispatch gate — the availability layer's entry.

    For every shard not already LOST, fires the
    ``distributed.<algo>.<phase>.shard`` faultpoint (the injectable
    stand-in for a dead host's dispatch error; ``phase`` defaults to
    "search" — the five search algos' long-standing sites — and the
    distributed coarse k-means fit passes "fit") and folds the verdict
    into the health registry: a failing shard is dropped from THIS
    dispatch (its candidates never enter the merge) and marked
    SUSPECT/LOST for the next.

    An active hard :class:`~raft_tpu.resilience.Deadline` slices its
    remaining budget evenly across the shards still to be probed — a shard
    that burns its slice (hang-class failure) costs its slice, not the
    query: it is dropped and the remainder re-sliced over the survivors.
    An expired OUTER budget still propagates.

    Raises :class:`~raft_tpu.resilience.ShardQuorumError` when the
    surviving coverage falls below the registry's minimum-coverage quorum.
    """
    health = health or resilience.shard_health()
    site = f"distributed.{algo}.{phase}.shard"
    world = int(world)
    rows = shard_rows_held(world, n_total)
    dl = resilience.active_deadline()
    ok = []
    enabled = obs.enabled()
    # per-shard wall times (round 19, telemetry-gated — NOOP mode pays no
    # clock reads): a failing shard's probe spends exception handling +
    # classification + health bookkeeping where a healthy one spends a
    # bare faultpoint check, so the max/median ratio spikes exactly when a
    # shard drags — the straggler signal the flight recorder windows fold
    shard_times = [] if enabled else None
    probe_attrs = ({"shard": world} if enabled else None)
    probe_span = obs.record_span("distributed::shard_probe",
                                 attrs=probe_attrs)
    with probe_span:
        for r in range(world):
            if health.state(r) == resilience.LOST:
                ok.append(False)
                continue
            t_shard = time.perf_counter() if enabled else 0.0
            try:
                if dl is not None and dl.hard:
                    left = sum(1 for rr in range(r, world)
                               if health.state(rr) != resilience.LOST)
                    slice_s = max(dl.remaining(), 0.0) / max(1, left)
                    with resilience.Deadline(slice_s, hard=True,
                                             label=f"{site}[{r}]"):
                        resilience.faultpoint(site)
                else:
                    resilience.faultpoint(site)
                health.report_success(r)
                ok.append(True)
            except InterruptedException:
                raise  # cross-thread cancel kills the query, never a shard
            except Exception as e:
                kind = resilience.classify(e)
                if kind == resilience.DEADLINE and (
                        dl is None or (dl.hard and dl.reached())):
                    # the QUERY's budget is spent (or there was no per-shard
                    # slice to absorb it) — propagate, don't blame the shard
                    raise
                health.report_failure(r, e)
                ok.append(False)
            if enabled:
                shard_times.append(time.perf_counter() - t_shard)
        if enabled and shard_times:
            ordered = sorted(shard_times)
            med = ordered[len(ordered) // 2]
            skew = round(max(shard_times) / max(med, 1e-9), 3)
            obs.set_gauge("distributed.shard_skew", skew)
            probe_span.set_attr("skew", skew)
    ok_np = np.asarray(ok, dtype=bool)
    covered = sum(rows[r] for r in range(world) if ok_np[r])
    coverage = covered / max(1, int(n_total))
    dropped = tuple(int(r) for r in range(world) if not ok_np[r])
    degraded = bool(dropped)
    if degraded:
        health.check_quorum(coverage, context=site)
        obs.add("distributed.partial_merge")
        record_event("partial_merge", site=site, coverage=round(coverage, 4),
                     dropped=list(dropped))
    return ShardReport(ok_np, coverage, degraded, dropped)


def shard_ok_device(ok: np.ndarray, comms):
    """Place a (world,) serving mask as a (world, 1) fp32 array sharded over
    the mesh axis, so each SPMD shard body reads its own flag (``ok[0, 0]``)
    and masks its candidates out of the merge when it is marked dead. A
    traced array input: flipping the mask never recompiles the search."""
    arr = jnp.asarray(np.asarray(ok, np.float32).reshape(-1, 1))
    return jax.device_put(arr, comms.sharding(comms.axis, None))


def shard_rows(work, comms):
    """Pad rows to a multiple of the communicator size and place them with a
    leading (world,) sharded dimension. Padded rows carry global id -1."""
    world = comms.size
    n, dim = work.shape
    rows_per = -(-n // world)
    n_pad = rows_per * world
    work_p = jnp.pad(work, ((0, n_pad - n), (0, 0)))
    gids = jnp.where(jnp.arange(n_pad) < n, jnp.arange(n_pad), -1).astype(jnp.int32)
    work_sh = jax.device_put(
        work_p.reshape(world, rows_per, dim),
        comms.sharding(comms.axis, None, None))
    gids_sh = jax.device_put(
        gids.reshape(world, rows_per), comms.sharding(comms.axis, None))
    return work_sh, gids_sh, rows_per


def assign_phase(work_sh, gids_sh, centers, km_metric, cap, n_lists, comms):
    """SPMD assign + spill per shard. Returns (labels_sh, counts_np) where
    labels use the sentinel ``n_lists`` for padded rows (dropped at pack)
    and counts_np (world, n_lists) counts real rows only.

    The spill itself runs over ALL local rows (padding included) so its
    rank/offset bookkeeping matches the labels array; the ≤ world-1 padded
    zero rows behave as ordinary data during the spill and are exiled to
    the sentinel afterwards."""

    def body(rows, ids):
        rows, ids = rows[0], ids[0]
        _, labels = kmeans_balanced._assign(rows, centers, km_metric)
        if cap:
            counts_all = jnp.bincount(labels, length=n_lists)
            labels, _ = _packing._spill_core(
                rows, centers, labels, km_metric, cap,
                jnp.zeros(n_lists, jnp.int32), counts_all, 65536)
        valid = ids >= 0
        counts = jax.ops.segment_sum(
            valid.astype(jnp.int32), jnp.where(valid, labels, 0),
            num_segments=n_lists).astype(jnp.int32)
        labels = jnp.where(valid, labels, n_lists)
        return labels[None], counts[None]

    axis = comms.axis
    fn = jax.jit(shard_map(
        body, mesh=comms.mesh,
        in_specs=(P(axis, None, None), P(axis, None)),
        out_specs=(P(axis, None), P(axis, None)),
        check_vma=False,
    ))
    resilience.faultpoint("distributed.assign_phase")
    assign_attrs = None
    if obs.enabled():
        obs.add("distributed.assign.shards", comms.size)
        obs.add("distributed.assign.rows",
                int(work_sh.shape[0]) * int(work_sh.shape[1]))
        assign_attrs = {"shard": int(comms.size),
                        "rows": int(work_sh.shape[0]) * int(work_sh.shape[1])}
    with obs.record_span("distributed::assign_phase", attrs=assign_attrs):
        labels_sh, counts_sh = fn(work_sh, gids_sh)
        counts_np = np.asarray(counts_sh)
    return labels_sh, counts_np


def round_mls(max_count: int, group: int) -> int:
    """Common padded list size: group-aligned; power-of-two 512-chunks when
    the strip backend's granule is in play (ops/strip_scan.py). Delegates
    to THE shared formula (_packing.round_list_size) so distributed and
    single-host builds can never disagree on mls."""
    from raft_tpu.neighbors._packing import round_list_size

    return round_list_size(max_count, group, pow2_chunks=group == 512)


def scatter_pack(labels, order_payloads, n_lists: int, mls: int):
    """Scatter sorted rows into (n_lists, mls, ...) blocks; sentinel labels
    (== n_lists) scatter out of range and are dropped.

    labels: (rp,) with sentinel for invalid rows. order_payloads: list of
    (init_array, values) pairs already in label-sorted order."""
    rp = labels.shape[0]
    order = jnp.argsort(labels)
    sorted_labels = labels[order]
    counts = jnp.bincount(jnp.minimum(labels, n_lists), length=n_lists + 1)
    offsets = (jnp.cumsum(counts) - counts)[:n_lists]
    off_of = jnp.where(sorted_labels < n_lists,
                       offsets[jnp.minimum(sorted_labels, n_lists - 1)], 0)
    pos = jnp.arange(rp, dtype=jnp.int32) - off_of.astype(jnp.int32)
    tgt_l = jnp.minimum(sorted_labels, n_lists)
    outs = []
    for init, values in order_payloads:
        outs.append(init.at[tgt_l, pos].set(values[order], mode="drop"))
    return outs


def merge_shards(vals, ids, k: int, axis: str, world: int = 0,
                 select_min: bool = True):
    """Cross-shard candidate exchange + exact re-select (knn_merge_parts
    analog, reference neighbors/detail/knn_merge_parts.cuh:140).

    Round-5 (VERDICT r4 #6): for power-of-two worlds the merge is a
    recursive-doubling butterfly — log2(world) rounds of pairwise
    ``ppermute`` + a narrow (2k → k) re-select. Per-link traffic is
    k·log2(world) candidate rows instead of the all_gather's k·world, so
    the merge stops growing linearly in world (the round-4 ICI sweep
    measured ~9× per-link byte growth from 2→8 devices; this is the fix).
    Top-k-merge is associative and commutative and shard id sets are
    disjoint, so the butterfly reduction is exact; every device ends with
    the identical replicated (q, k) result, as before. ``world = 0`` (or a
    non-power-of-two size) falls back to the all_gather merge."""
    bad = jnp.float32(jnp.inf if select_min else -jnp.inf)
    if world > 1 and (world & (world - 1)) == 0:
        step = 1
        while step < world:
            perm = [(i, i ^ step) for i in range(world)]
            ov = jax.lax.ppermute(vals, axis, perm)
            oi = jax.lax.ppermute(ids, axis, perm)
            cat_v = jnp.concatenate([vals, ov], axis=1)
            cat_i = jnp.concatenate([ids, oi], axis=1)
            key = jnp.where(cat_i >= 0, cat_v, bad)
            vals, sel = select_k(key, k, select_min=select_min)
            ids = jnp.take_along_axis(cat_i, sel, axis=1)
            step <<= 1
        return jnp.where(ids >= 0, vals, bad), ids
    all_vals = jax.lax.all_gather(vals, axis, axis=1, tiled=True)
    all_ids = jax.lax.all_gather(ids, axis, axis=1, tiled=True)
    key = jnp.where(all_ids >= 0, all_vals, bad)
    out_v, sel = select_k(key, k, select_min=select_min)
    out_i = jnp.take_along_axis(all_ids, sel, axis=1)
    return jnp.where(out_i >= 0, out_v, bad), out_i


@functools.lru_cache(maxsize=64)
def make_tile_fn(mesh, axis, class_layout, k, kf, dense, interpret, alpha,
                 world=0, scan="strip"):
    """shard_map'd search tile shared by the distributed IVF indexes: local
    scan on the shard's (data, ids, bias[, scale]) operands + butterfly
    merge. ``scan`` picks the engine: "strip" (fp/int8 B operand — strip
    kernel, or dense gather for sub-512 lists) or "bq" (packed 1-bit codes
    with the per-entry correction scale, ops/bq_scan). Bias carries +inf
    at padding (precomputed at build). ``ok`` is the (world, 1) serving
    mask (shard_ok_device): a dead shard's candidates are blanked to
    (+inf, -1) BEFORE the merge, so the partial merge is exact over the
    survivors."""
    from raft_tpu.ops.strip_scan import _strip_tile_body

    def body(queries, probes, pair_const, qids, strip_list, pair_strip,
             pair_slot, data, ids_arr, bias, scale, ok):
        ld, li, b = data[0], ids_arr[0], bias[0]
        if scan == "bq":
            from raft_tpu.ops import bq_scan

            sc = scale[0]
            if dense:
                vals, ids = bq_scan.bq_dense_scan(
                    queries, probes, ld, sc, b, li, k, alpha, pair_const)
            else:
                vals, ids = bq_scan._bq_tile_body(
                    queries, qids, strip_list, pair_strip, pair_slot,
                    ld, sc, b, li, class_layout, k, kf, alpha, interpret,
                    pair_const, approx_ok=True,
                )
        elif dense:
            vals, ids = dense_local_scan(queries, probes, ld, b, li, k,
                                         alpha, pair_const)
        else:
            vals, ids = _strip_tile_body(
                queries, qids, strip_list, pair_strip, pair_slot,
                ld, b, li, class_layout, k, kf, alpha, interpret,
                pair_const,
            )
        alive = ok[0, 0] > 0
        vals = jnp.where(alive, vals, jnp.inf)
        ids = jnp.where(alive, ids, -1)
        return merge_shards(vals, ids, k, axis, world)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P(), P(),
                  P(axis, None, None, None), P(axis, None, None),
                  P(axis, None, None), P(axis, None, None), P(axis, None)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def tiled_search(queries_mat, probes, lens_max, n_lists, k, comms,
                 alpha, dense, interpret, data, ids_arr, bias,
                 pair_const=None, algo="ivf", n_total=0, health=None,
                 scale=None, scan="strip"):
    """Query-tiled SPMD search loop shared by the distributed IVF indexes.
    ``scale`` is the optional (world, n_lists, mls) per-entry multiplicative
    operand (the BQ correction scalar) and ``scan`` the engine selector —
    see :func:`make_tile_fn`.

    Plans are built ON DEVICE (ops/strip_scan._plan_device, replicated —
    every shard runs the identical grid from the per-list MAX fill) and the
    host fetches only the per-class strip counts; round-3: host-built plan
    tables cost several MB of ~25 MB/s uploads per tile on the tunneled
    runtime. ``probes`` is a device array — no host copy of it exists.

    Returns ``(vals, ids, report)``: the dispatch runs through
    :func:`probe_shards` first, so a dead shard costs coverage (its
    candidates are masked out of every tile's merge), not the query."""
    from raft_tpu.core.resources import current_resources
    from raft_tpu.ops.strip_scan import class_info, fit_q_tile, plan_tile

    if not dense and k > 512:
        raise ValueError(
            f"distributed strip search supports k <= 512, got {k}"
        )
    if n_total <= 0:
        raise ValueError("tiled_search needs the true row count (n_total) "
                         "for coverage accounting")
    report = probe_shards(algo, comms.size, n_total, health=health)
    ok_dev = shard_ok_device(report.ok, comms)
    if scale is None:
        # strip/dense scans ignore the operand: a (world, 1, 1) placeholder
        # keeps the shard_map signature static across engines
        scale = jax.device_put(jnp.zeros((comms.size, 1, 1), jnp.float32),
                               comms.sharding(comms.axis, None, None))
    kf = min(int(k), 512)
    q = queries_mat.shape[0]
    probes = jnp.asarray(probes)
    p = probes.shape[1]
    if pair_const is None:
        pair_const = jnp.zeros((q, p), jnp.float32)
    classes, cls_ord_np = class_info(np.asarray(lens_max),
                                     dim=queries_mat.shape[1])
    cls_ord = jnp.asarray(cls_ord_np)
    q_tile = fit_q_tile(q, p, n_lists, len(classes), kf,
                        current_resources().workspace_bytes,
                        dim=queries_mat.shape[1])
    out_v, out_i = [], []
    start = 0
    n_tiles = 0
    zero = jnp.zeros((1,), jnp.int32)
    zero2 = jnp.zeros((1, 1), jnp.int32)
    from raft_tpu.core.interruptible import check_interrupt

    search_attrs = None
    if obs.enabled():
        from raft_tpu.obs import tracing as obs_tracing

        search_attrs = {"shard": int(comms.size), "queries": int(q),
                        "probes": int(q * p),
                        "coverage": round(report.coverage, 4),
                        # fleet-deterministic dispatch id (round 19): every
                        # host stamps the SAME id on the same SPMD dispatch,
                        # so the trace stitcher joins per-host tracks into
                        # one fleet trace on this attr
                        "fleet_trace_id": obs_tracing.fleet_trace_id(
                            "distributed.tiled_search")}
    span = obs.record_span("distributed::tiled_search", attrs=search_attrs)
    with span:
        while start < q:
            check_interrupt()  # per-tile checkpoint: cancel/hard-deadline
            # land between dispatches, not after the full query set
            resilience.faultpoint("distributed.tiled_search.tile")
            qt = min(q_tile, q - start)
            with obs.record_span("distributed::search_tile",
                                 attrs=({"tile": n_tiles, "rows": int(qt)}
                                        if obs.enabled() else None)):
                if dense:
                    # dense_local_scan never reads the strip tables: skip
                    # the planning dispatch + its counts round-trip entirely
                    qids, strip_list, pair_strip, pair_slot = (
                        zero2, zero, zero2, zero2)
                    layout = ((1, 1, 0, 1),)
                else:
                    qids, strip_list, pair_strip, pair_slot, layout = \
                        plan_tile(probes, start, qt, cls_ord, classes,
                                  n_lists)
                fn = make_tile_fn(comms.mesh, comms.axis, layout, int(k),
                                  kf, dense, interpret, alpha, comms.size,
                                  scan)
                v, i = fn(queries_mat[start:start + qt],
                          jax.lax.slice_in_dim(probes, start, start + qt,
                                               axis=0),
                          pair_const[start:start + qt],
                          qids, strip_list, pair_strip, pair_slot,
                          data, ids_arr, bias, scale, ok_dev)
            out_v.append(v)
            out_i.append(i)
            start += qt
            n_tiles += 1
        # discovered only after the loop — attach before the span closes
        span.set_attr("tiles", n_tiles)
    if obs.enabled():
        obs.add("distributed.search.shards", comms.size)
        obs.add("distributed.search.queries", q)
        obs.add("distributed.search.probes", q * p)
        obs.add("distributed.search.tiles", n_tiles)
    vals = out_v[0] if len(out_v) == 1 else jnp.concatenate(out_v, 0)
    ids = out_i[0] if len(out_i) == 1 else jnp.concatenate(out_i, 0)
    return vals, ids, report


def dense_local_scan(queries, probes, ld, bias, li, k: int, alpha: float,
                     pair_const=None):
    """Jittable dense fallback scan for shards too small for the strip
    kernel (max_list_size < 512), and the off-TPU SPMD scan.

    Tiled over the probe axis (``lax.map``): the one-shot formulation
    materialized a (q, p, mls, dim) gather — 2 GB/device at the ICI-bench
    shapes, which collapsed the virtual-mesh weak-scaling run — where one
    probe's (q, mls, dim) block is p× smaller and the loop carries only
    the (p, q, mls) score tensor."""
    q = queries.shape[0]
    qf = queries.astype(jnp.float32)

    def one_probe(j):
        lids = probes[:, j]                              # (q,)
        cand = ld[lids].astype(jnp.float32)              # (q, mls, d)
        ip = jnp.einsum("qd,qmd->qm", qf, cand,
                        preferred_element_type=jnp.float32)
        d = alpha * ip + bias[lids]
        if pair_const is not None:
            d = d + pair_const[:, j, None]
        return d, li[lids]

    p = probes.shape[1]
    d_all, ids_all = lax.map(one_probe, jnp.arange(p))   # (p, q, mls)
    d = jnp.transpose(d_all, (1, 0, 2)).reshape(q, -1)
    flat_ids = jnp.transpose(ids_all, (1, 0, 2)).reshape(q, -1)
    vals, sel = select_k(d, min(k, d.shape[1]), select_min=True)
    ids = jnp.where(jnp.isinf(vals), -1,
                    jnp.take_along_axis(flat_ids, sel, axis=1))
    if ids.shape[1] < k:
        vals = jnp.pad(vals, ((0, 0), (0, k - ids.shape[1])),
                       constant_values=jnp.inf)
        ids = jnp.pad(ids, ((0, 0), (0, k - ids.shape[1])),
                      constant_values=-1)
    return vals, ids
