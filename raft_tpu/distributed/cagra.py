"""Sharded CAGRA: per-shard local graphs, replicated queries, one
``shard_map`` search with a butterfly (recursive-doubling) candidate merge.

Reference pattern: the raft-dask MNMG ANN layout
(python/raft-dask/raft_dask/common/comms.py:40 — every worker owns an
independent index over its data partition, queries broadcast, results
merged with knn_merge_parts, neighbors/detail/knn_merge_parts.cuh:140).
CAGRA has no intra-index distribution in the reference either: the graph's
irregular traversal makes cross-worker hops latency-bound, so the MNMG
recipe is shard-local graphs + a k-way merge, which scales the DATA (each
chip holds n/world rows and walks a graph that fits its HBM) while the
merge rides one ICI all-gather of (world·k) candidates per query.

Build here loops shards on the host (this process owns the whole virtual
mesh); on a real multi-host pod each process builds only its local shard —
the per-shard builds are embarrassingly parallel.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import Comms, make_comms
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.neighbors import cagra as sl

# padded shard rows get this coordinate value: any query's distance to the
# sentinel row is ~1e36, so it can never enter a top-k
_PAD_SENTINEL = 1e18


@dataclass
class ShardedCagraIndex:
    """Row-sharded CAGRA: one local graph per shard, stacked on a leading
    (world,) mesh dimension. Graph ids are shard-LOCAL; the search maps
    them to global ids (rank · rows_per + local)."""

    dataset: jax.Array   # (world, rows_per, dim) fp32, P(axis)
    graph: jax.Array     # (world, rows_per, graph_degree) int32, P(axis)
    n_total: int
    comms: Comms

    @property
    def dim(self) -> int:
        return self.dataset.shape[2]

    @property
    def size(self) -> int:
        return self.n_total

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[2]

    @property
    def rows_per_shard(self) -> int:
        return self.dataset.shape[1]


def build(
    dataset,
    params: sl.CagraParams = sl.CagraParams(),
    comms: Optional[Comms] = None,
    res: Optional[Resources] = None,
) -> ShardedCagraIndex:
    """Per-shard CAGRA builds over a row partition (host loop; parallel
    across processes on a real pod)."""
    res = res or current_resources()
    comms = comms or make_comms(res)
    world = comms.size
    X = jnp.asarray(dataset, jnp.float32)
    n, dim = X.shape
    rows_per = -(-n // world)
    if rows_per <= params.graph_degree:
        raise ValueError(
            f"shard rows {rows_per} must exceed graph_degree "
            f"{params.graph_degree}")
    ds_parts, g_parts = [], []
    for r in range(world):
        Xr = X[r * rows_per: min((r + 1) * rows_per, n)]
        li = sl.build(Xr, params, res=res)
        pad = rows_per - Xr.shape[0]
        d = li.dataset.astype(jnp.float32)
        g = li.graph
        if pad:
            d = jnp.pad(d, ((0, pad), (0, 0)),
                        constant_values=_PAD_SENTINEL)
            g = jnp.pad(g, ((0, pad), (0, 0)), constant_values=-1)
        ds_parts.append(d)
        g_parts.append(g)
    dataset_sh = jax.device_put(jnp.stack(ds_parts),
                                comms.sharding(comms.axis, None, None))
    graph_sh = jax.device_put(jnp.stack(g_parts),
                              comms.sharding(comms.axis, None, None))
    return ShardedCagraIndex(dataset_sh, graph_sh, n, comms)


@functools.lru_cache(maxsize=64)
def _make_search_fn(mesh, axis, k, itopk, width, max_iter, min_iter, n_rand,
                    n_total, seed, world=0):
    def body(shard, graph, queries):
        rows = shard.shape[1]
        rank = jax.lax.axis_index(axis)
        key = jax.random.key(seed)
        vals, local_ids = sl._search_impl(
            shard[0], graph[0], queries, key, None, rows,
            k, itopk, width, max_iter, min_iter, n_rand)
        gids = jnp.where(local_ids >= 0,
                         rank * rows + local_ids, -1).astype(jnp.int32)
        # padded sentinel rows carry ~1e36 distances already; also mask any
        # global id beyond the true row count
        bad = (gids < 0) | (gids >= n_total)
        vals = jnp.where(bad, jnp.inf, vals)
        gids = jnp.where(bad, -1, gids)
        from raft_tpu.distributed._sharding import merge_shards

        return merge_shards(vals, gids, k, axis, world)

    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None, None), P(axis, None, None), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def search(
    index: ShardedCagraIndex,
    queries,
    k: int,
    params: sl.CagraSearchParams = sl.CagraSearchParams(),
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """SPMD CAGRA search: every shard walks its local graph, one all-gather
    merges the (world·k) candidates exactly. Returns (distances (q, k),
    GLOBAL row ids (q, k)), replicated."""
    queries = jnp.asarray(queries, jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries must be (q, {index.dim})")
    itopk = int(min(params.itopk_size, index.rows_per_shard))
    if not 0 < k <= itopk:
        raise ValueError(f"k={k} must be in (0, itopk_size={itopk}]")
    width = int(params.search_width)
    max_iter = int(params.max_iterations) or max(16, itopk // width)
    min_iter = int(min(params.min_iterations, max_iter))
    fn = _make_search_fn(
        index.comms.mesh, index.comms.axis, int(k), itopk, width, max_iter,
        min_iter, int(max(1, params.num_random_samplings)), index.n_total,
        int(params.seed), index.comms.size)
    return fn(index.dataset, index.graph, queries)
