"""Sharded CAGRA: per-shard local graphs, replicated queries, one
``shard_map`` search with a butterfly (recursive-doubling) candidate merge.

Reference pattern: the raft-dask MNMG ANN layout
(python/raft-dask/raft_dask/common/comms.py:40 — every worker owns an
independent index over its data partition, queries broadcast, results
merged with knn_merge_parts, neighbors/detail/knn_merge_parts.cuh:140).
CAGRA has no intra-index distribution in the reference either: the graph's
irregular traversal makes cross-worker hops latency-bound, so the MNMG
recipe is shard-local graphs + a k-way merge, which scales the DATA (each
chip holds n/world rows and walks a graph that fits its HBM) while the
merge rides one ICI all-gather of (world·k) candidates per query.

Build here loops shards on the host (this process owns the whole virtual
mesh); on a real multi-host pod each process builds only its local shard —
the per-shard builds are embarrassingly parallel.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import Comms, make_comms
from raft_tpu.core.compat import shard_map
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.trace import traced
from raft_tpu.neighbors import cagra as sl

# padded shard rows get this coordinate value: any query's distance to the
# sentinel row is ~1e36, so it can never enter a top-k
_PAD_SENTINEL = 1e18


@dataclass
class ShardedCagraIndex:
    """Row-sharded CAGRA: one local graph per shard, stacked on a leading
    (world,) mesh dimension. Graph ids are shard-LOCAL; the search maps
    them to global ids (rank · rows_per + local).

    When every shard was built with the compressed-traversal payload
    (CagraParams.compress), the stacked payload rides along and the SPMD
    search runs each shard's compressed loop (round 5); otherwise the
    full-precision loop."""

    dataset: jax.Array   # (world, rows_per, dim) fp32, P(axis)
    graph: jax.Array     # (world, rows_per, graph_degree) int32, P(axis)
    n_total: int
    comms: Comms
    proj: Optional[jax.Array] = None        # (world, dim, p), P(axis)
    code_scale: Optional[jax.Array] = None  # (world,), P(axis)
    nbr_codes: Optional[jax.Array] = None   # (world, rows_per, deg, p) int8
    centroids: Optional[jax.Array] = None   # (world, c, dim), P(axis)
    centroid_reps: Optional[jax.Array] = None  # (world, c) int32, LOCAL ids
    proj_energy: Optional[jax.Array] = None    # (world,), P(axis)

    @property
    def dim(self) -> int:
        return self.dataset.shape[2]

    @property
    def size(self) -> int:
        return self.n_total

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[2]

    @property
    def rows_per_shard(self) -> int:
        return self.dataset.shape[1]


@traced("distributed.cagra::build")
def build(
    dataset,
    params: sl.CagraParams = sl.CagraParams(),
    comms: Optional[Comms] = None,
    res: Optional[Resources] = None,
) -> ShardedCagraIndex:
    """Per-shard CAGRA builds over a row partition (host loop; parallel
    across processes on a real pod)."""
    res = res or current_resources()
    comms = comms or make_comms(res)
    world = comms.size
    X = jnp.asarray(dataset, jnp.float32)
    n, dim = X.shape
    rows_per = -(-n // world)
    if rows_per <= params.graph_degree:
        raise ValueError(
            f"shard rows {rows_per} must exceed graph_degree "
            f"{params.graph_degree}")
    # resolve compress="auto" ONCE from the GLOBAL row count: per-shard
    # re-derivation lets one sub-threshold tail shard silently discard
    # every other shard's built payload (code-review r5)
    import dataclasses as _dc

    compress_on = params.compress == "on" or (
        params.compress == "auto" and n >= params.compress_threshold)
    params = _dc.replace(params, compress="on" if compress_on else "off")
    ds_parts, g_parts = [], []
    payload = {k: [] for k in ("proj", "code_scale", "nbr_codes",
                               "centroids", "centroid_reps", "proj_energy")}
    for r in range(world):
        Xr = X[r * rows_per: min((r + 1) * rows_per, n)]
        li = sl.build(Xr, params, res=res)
        pad = rows_per - Xr.shape[0]
        d = li.dataset.astype(jnp.float32)
        g = li.graph
        if pad:
            d = jnp.pad(d, ((0, pad), (0, 0)),
                        constant_values=_PAD_SENTINEL)
            g = jnp.pad(g, ((0, pad), (0, 0)), constant_values=-1)
        ds_parts.append(d)
        g_parts.append(g)
        if li.nbr_codes is not None:
            payload["proj"].append(li.proj)
            payload["code_scale"].append(li.code_scale)
            payload["nbr_codes"].append(jnp.pad(
                li.nbr_codes, ((0, pad), (0, 0), (0, 0))) if pad
                else li.nbr_codes)
            payload["centroids"].append(li.centroids)
            payload["centroid_reps"].append(li.centroid_reps)
            payload["proj_energy"].append(
                li.proj_energy if li.proj_energy is not None
                else jnp.float32(li.proj.shape[1] / dim))

    def put(parts, spec_extra):
        return jax.device_put(
            jnp.stack(parts),
            comms.sharding(comms.axis, *spec_extra))

    dataset_sh = put(ds_parts, (None, None))
    graph_sh = put(g_parts, (None, None))
    opt = {}
    # the payload rides only when EVERY shard built it (identical params →
    # all or none); the centroid seeding table additionally needs every
    # shard to have one of the same shape (small shards skip centroids and
    # seed randomly inside the compressed loop)
    core = ("proj", "code_scale", "nbr_codes", "proj_energy")
    if (len(payload["nbr_codes"]) == world
            and all(x is not None for kk in core for x in payload[kk])):
        opt = {
            "proj": put(payload["proj"], (None, None)),
            "code_scale": put(payload["code_scale"], ()),
            "nbr_codes": put(payload["nbr_codes"], (None, None, None)),
            "proj_energy": put(payload["proj_energy"], ()),
        }
        cents = payload["centroids"]
        if (all(c is not None for c in cents)
                and len({c.shape for c in cents}) == 1):
            opt["centroids"] = put(cents, (None, None))
            opt["centroid_reps"] = put(payload["centroid_reps"], (None,))
    return ShardedCagraIndex(dataset_sh, graph_sh, n, comms, **opt)


@functools.lru_cache(maxsize=64)
def _make_search_fn(mesh, axis, k, itopk, width, max_iter, min_iter, n_rand,
                    n_total, seed, world=0, compressed=False, rt=0,
                    has_cents=False):
    def body(shard, graph, queries, ok, *payload):
        rows = shard.shape[1]
        rank = jax.lax.axis_index(axis)
        key = jax.random.key(seed)
        if compressed:
            if has_cents:
                proj, scale, codes, cents, reps, energy = payload
                cents, reps = cents[0], reps[0]
            else:
                proj, scale, codes, energy = payload
                cents = reps = None
            vals, local_ids = sl._search_impl_compressed(
                shard[0], graph[0], codes[0], proj[0], scale[0],
                cents, reps, energy[0], queries, key, None, rows,
                k, itopk, width, max_iter, min_iter, n_rand, rt)
        else:
            vals, local_ids = sl._search_impl(
                shard[0], graph[0], queries, key, None, rows,
                k, itopk, width, max_iter, min_iter, n_rand)
        gids = jnp.where(local_ids >= 0,
                         rank * rows + local_ids, -1).astype(jnp.int32)
        # padded sentinel rows carry ~1e36 distances already; also mask any
        # global id beyond the true row count — and a dead shard's whole
        # candidate list (degraded mode: coverage, not availability)
        alive = ok[0, 0] > 0
        bad = (gids < 0) | (gids >= n_total) | ~alive
        vals = jnp.where(bad, jnp.inf, vals)
        gids = jnp.where(bad, -1, gids)
        from raft_tpu.distributed._sharding import merge_shards

        return merge_shards(vals, gids, k, axis, world)

    if compressed:
        pay_specs = (P(axis, None, None), P(axis),
                     P(axis, None, None, None))
        if has_cents:
            pay_specs += (P(axis, None, None), P(axis, None))
        pay_specs += (P(axis),)
    else:
        pay_specs = ()
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None, None), P(axis, None, None), P(),
                  P(axis, None)) + pay_specs,
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


@traced("distributed.cagra::search")
def search(
    index: ShardedCagraIndex,
    queries,
    k: int,
    params: sl.CagraSearchParams = sl.CagraSearchParams(),
    res: Optional[Resources] = None,
    health=None,
) -> Tuple[jax.Array, jax.Array]:
    """SPMD CAGRA search: every shard walks its local graph, one all-gather
    merges the (world·k) candidates exactly. Returns (distances (q, k),
    GLOBAL row ids (q, k)), replicated, as a
    :class:`~raft_tpu.distributed._sharding.SearchResult` (carries
    ``coverage``/``degraded`` when shards were dropped)."""
    queries = jnp.asarray(queries, jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries must be (q, {index.dim})")
    itopk = int(min(params.itopk_size, index.rows_per_shard))
    if not 0 < k <= itopk:
        raise ValueError(f"k={k} must be in (0, itopk_size={itopk}]")
    width = int(params.search_width)
    max_iter = int(params.max_iterations) or max(16, itopk // width)
    min_iter = int(min(params.min_iterations, max_iter))
    # allow_fused=False: the fused Pallas hop is a single-device kernel;
    # shard bodies ride the unfused compressed loop (traversal="fused"
    # downgrades, "auto" resolves straight to compressed here)
    mode, rt = sl._resolve_traversal(params, index.nbr_codes is not None,
                                     int(k), itopk,
                                     size=index.rows_per_shard,
                                     allow_fused=False,
                                     b=width * index.graph_degree)
    compressed = mode == "compressed"
    has_cents = compressed and index.centroids is not None
    fn = _make_search_fn(
        index.comms.mesh, index.comms.axis, int(k), itopk, width, max_iter,
        min_iter, int(max(1, params.num_random_samplings)), index.n_total,
        int(params.seed), index.comms.size, compressed, rt, has_cents)
    from raft_tpu.distributed._sharding import (SearchResult, probe_shards,
                                                shard_ok_device)

    report = probe_shards("cagra", index.comms.size, index.n_total,
                          health=health)
    ok_dev = shard_ok_device(report.ok, index.comms)
    if compressed:
        args = (index.proj, index.code_scale, index.nbr_codes)
        if has_cents:
            args += (index.centroids, index.centroid_reps)
        args += (index.proj_energy,)
        vals, ids = fn(index.dataset, index.graph, queries, ok_dev, *args)
    else:
        vals, ids = fn(index.dataset, index.graph, queries, ok_dev)
    return SearchResult(vals, ids, coverage=report.coverage,
                        degraded=report.degraded,
                        lost_shards=report.dropped)
