"""Multi-device IVF-PQ: globally trained quantizers, row-sharded code
lists, one ``shard_map`` search — the north-star configuration (BASELINE.md:
IVF-PQ on SIFT-1B over a TPU pod).

Reference analog: the raft-dask MNMG pattern (one model per worker sharing
centrally trained parameters, collectives for the merge —
python/raft-dask/raft_dask/common/comms.py:40, knn_merge_parts.cuh:140)
re-expressed as SPMD over a mesh, so it runs multi-host unchanged.

Division of labor:
  * **Global, replicated**: coarse centers (data-sharded k-means, psum over
    shards), rotation matrix, per-subspace codebooks (trained on a
    subsample — the reference trains on a host-side subsample too,
    ivf_pq_build.cuh:1729). Every shard encodes/probes identically.
  * **Per shard**: its rows' PQ codes packed into padded lists, b_sum, and
    the int8 residual strip-scan cache. The dequant scale is
    max|codebooks|/127 — exact, data-independent, identical on every shard
    with no collective (the −2⟨q, R·c_l⟩ center term rides the merge's
    exact pair_const instead of the cache).
  * **Search**: identical strip-scan plan on every shard (per-list MAX fill
    across shards), local scan, butterfly candidate merge (k·log2(world)
    per-link bytes — _sharding.merge_shards). Pipe through
    neighbors/refine (sharded refine: the candidate ids are global) for
    the re-ranked headline configuration.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.comms.comms import Comms, make_comms
from raft_tpu.core.compat import shard_map
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.trace import traced
from raft_tpu.neighbors import _packing
from raft_tpu.neighbors import ivf_pq as sl
from raft_tpu.neighbors.ivf_pq import IvfPqParams
from raft_tpu.ops import distance as dist_mod


@dataclass
class ShardedIvfPqIndex:
    """Row-sharded IVF-PQ: replicated quantizers, per-shard code lists and
    int8 decoded cache stacked on a leading (world,) mesh dimension."""

    centers: jax.Array       # (n_lists, dim) replicated
    rotation: jax.Array      # (rot_dim, rot_dim) replicated
    codebooks: jax.Array     # (pq_dim, n_codes, dsub) replicated
    list_codes: jax.Array    # (world, n_lists, mls, pq_dim) uint8, P(axis)
    list_ids: jax.Array      # (world, n_lists, mls) int32, GLOBAL row ids
    # full per-entry scan bias, built once at build: ‖R·c_l‖² + b_sum for
    # L2 (b_sum for ip-family), +inf at padding (per-call rebuilds were one
    # wasted index-sized pass per search)
    bias: jax.Array          # (world, n_lists, mls) fp32, P(axis)
    decoded: jax.Array       # (world, n_lists, mls, rot_dim) int8, P(axis)
    decoded_scale: float     # replicated dequant scale (analytic bound)
    metric: str
    pq_bits: int
    n_total: int
    comms: Comms
    lens_max: np.ndarray     # host (n_lists,) max per-list fill across shards

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def max_list_size(self) -> int:
        return self.list_codes.shape[2]


@traced("distributed.ivf_pq::build")
def build(
    dataset,
    params: IvfPqParams = IvfPqParams(),
    comms: Optional[Comms] = None,
    res: Optional[Resources] = None,
) -> ShardedIvfPqIndex:
    """Global quantizers + one SPMD assign/spill phase + one SPMD
    encode/pack/decode phase."""
    res = res or current_resources()
    comms = comms or make_comms()
    world = comms.size
    axis = comms.axis
    dataset = jnp.asarray(dataset).astype(jnp.float32)
    n, dim = dataset.shape
    if params.n_lists * world > n:
        raise ValueError(f"n_lists={params.n_lists} x {world} shards > n_rows={n}")
    cluster = params.codebook_kind == "cluster"
    pq_dim = params.pq_dim or sl._auto_pq_dim(dim)
    dsub = -(-dim // pq_dim)
    rot_dim = pq_dim * dsub
    n_codes = 1 << params.pq_bits

    work = dataset
    if params.metric == "cosine":
        work = work / jnp.maximum(jnp.linalg.norm(work, axis=1, keepdims=True), 1e-30)
    km_metric = ("inner_product" if params.metric in ("cosine", "inner_product")
                 else "sqeuclidean")

    # --- global coarse quantizer -------------------------------------------
    from raft_tpu.cluster.kmeans import KMeansParams
    from raft_tpu.distributed import kmeans as dkm

    out, _ = dkm.fit(
        work, KMeansParams(n_clusters=params.n_lists,
                           max_iter=params.kmeans_n_iters, seed=params.seed),
        comms=comms,
    )
    centers = out.centroids
    if params.metric in ("cosine", "inner_product"):
        centers = centers / jnp.maximum(
            jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-30)

    # --- global rotation + codebooks (subsample-trained, replicated) -------
    key = jax.random.key(params.seed)
    k_rot, k_cb, k_sub = jax.random.split(key, 3)
    rotation = sl.make_rotation_matrix(k_rot, rot_dim)
    cb_rows = min(n, 65536)
    sub_rows = jax.random.randint(k_sub, (cb_rows,), 0, n)
    sub = work[sub_rows]
    sub_labels = kmeans_balanced.predict(
        sub, centers, kmeans_balanced.KMeansBalancedParams(metric=km_metric),
        res=res)
    resid = sl._pad_rot(sub - centers[sub_labels], rot_dim) @ rotation.T
    if cluster:
        # PER_CLUSTER (ivf_pq_types.hpp:36): one codebook per IVF list,
        # trained on the replicated subsample — every shard computes the
        # identical (n_lists, n_codes, dsub) tensor with no collective
        codebooks = sl._train_codebooks_cluster(
            resid.reshape(cb_rows, pq_dim, dsub), sub_labels, k_cb,
            n_codes, params.codebook_n_iters, params.n_lists)
    else:
        resid_cb = resid.reshape(cb_rows, pq_dim, dsub).transpose(1, 0, 2)
        codebooks = sl._train_codebooks(resid_cb, k_cb, n_codes,
                                        params.codebook_n_iters)

    # --- shard rows + SPMD assign/spill phase (shared helpers) -------------
    from raft_tpu.distributed._sharding import (assign_phase, round_mls,
                                                scatter_pack, shard_rows)

    work_sh, gids_sh, rows_per = shard_rows(work, comms)
    group = params.group_size or _packing.auto_group_size(
        rows_per, params.n_lists, floor=128)
    cap = params.list_size_cap
    if cap < 0:
        cap = _packing.auto_list_cap(rows_per, params.n_lists, group)
    n_lists = params.n_lists
    labels_sh, counts_np = assign_phase(
        work_sh, gids_sh, centers, km_metric, cap, n_lists, comms)
    mls = round_mls(int(counts_np.max()), group)

    # replicated dequant scale for the residual-only cache: max|codebook|
    # is exact and identical on every shard for free (see
    # neighbors/ivf_pq._decode_lists)
    scale = float(jnp.maximum(jnp.max(jnp.abs(codebooks)), 1e-30) / 127.0)

    # --- phase 2 (SPMD): encode + pack + b_sum + int8 decode ---------------
    l2 = params.metric in ("sqeuclidean", "euclidean")

    code_w = sl.packed_width(pq_dim, params.pq_bits)

    def pack_body(rows, ids, labels):
        rows, ids, labels = rows[0], ids[0], labels[0]
        rp = rows.shape[0]
        safe_labels = jnp.minimum(labels, n_lists - 1)
        residual = sl._pad_rot(rows - centers[safe_labels], rot_dim) @ rotation.T
        resid3 = residual.reshape(rp, pq_dim, dsub)
        raw = (sl._encode_cluster(resid3, safe_labels, codebooks) if cluster
               else sl._encode(resid3, codebooks))
        codes = sl.pack_codes(raw, params.pq_bits)
        lc, li = scatter_pack(
            labels,
            [(jnp.zeros((n_lists, mls, code_w), jnp.uint8), codes),
             (jnp.full((n_lists, mls), -1, jnp.int32), ids)],
            n_lists, mls)
        b_sum = sl._compute_b_sum(centers, rotation, codebooks, lc, li,
                                  params.metric, pq_dim, params.pq_bits,
                                  cluster=cluster)
        if l2:  # fold the coarse-center norm in once (b_sum is +inf at pad)
            rc2 = dist_mod.sqnorm(sl._pad_rot(centers, rot_dim) @ rotation.T)
            bias = rc2[:, None] + b_sum
        else:
            bias = b_sum
        return lc[None], li[None], bias[None]

    pack_fn = jax.jit(shard_map(
        pack_body, mesh=comms.mesh,
        in_specs=(P(axis, None, None), P(axis, None), P(axis, None)),
        out_specs=(P(axis, None, None, None), P(axis, None, None),
                   P(axis, None, None)),
        check_vma=False,
    ))
    list_codes, list_ids, bias = pack_fn(work_sh, gids_sh, labels_sh)

    # decode with the replicated scale (separate pass so the scale logic
    # stays in one place)
    def decode_body(lc):
        return sl._decode_lists_scaled(codebooks, lc[0], scale, pq_dim,
                                       params.pq_bits, cluster=cluster)[None]

    decode_fn = jax.jit(shard_map(
        decode_body, mesh=comms.mesh,
        in_specs=(P(axis, None, None, None),),
        out_specs=P(axis, None, None, None),
        check_vma=False,
    ))
    decoded = decode_fn(list_codes)
    return ShardedIvfPqIndex(
        centers, rotation, codebooks, list_codes, list_ids, bias, decoded,
        scale, params.metric, params.pq_bits, n, comms,
        counts_np.max(axis=0).astype(np.int32),
    )


@traced("distributed.ivf_pq::search")
def search(
    index: ShardedIvfPqIndex,
    queries,
    k: int,
    n_probes: int = 20,
    res: Optional[Resources] = None,
    health=None,
) -> Tuple[jax.Array, jax.Array]:
    """SPMD IVF-PQ search over the sharded code lists. Returns PQ-approximate
    (distances (q, k), global row ids (q, k)) as a
    :class:`~raft_tpu.distributed._sharding.SearchResult` (replicated;
    carries ``coverage``/``degraded`` when shards were dropped); re-rank
    with neighbors/refine for the headline configuration."""
    from raft_tpu.distributed._sharding import SearchResult, tiled_search
    from raft_tpu.neighbors.ivf_flat import _coarse_probes
    from raft_tpu.ops.strip_scan import strip_eligible

    res = res or current_resources()
    queries = jnp.asarray(queries).astype(jnp.float32)
    if queries.shape[1] != index.dim:
        raise ValueError(f"query dim {queries.shape[1]} != index dim {index.dim}")
    if index.metric == "cosine":
        queries = queries / jnp.maximum(
            jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-30)
    n_probes = int(min(n_probes, index.n_lists))
    l2 = index.metric in ("sqeuclidean", "euclidean")

    alpha = -2.0 if l2 else -1.0
    # one gemm feeds both the coarse ranking and the exact per-pair center
    # term (rotation is orthogonal: raw centers work)
    probes, qr_scaled, _, pair_const = sl._pq_search_prep(
        queries, index.centers, index.rotation,
        jnp.zeros((1, 1), jnp.float32), jnp.full((1, 1), -1, jnp.int32),
        index.decoded_scale, None, n_probes, index.metric, "exact",
        res.compute_dtype, l2,
    )
    # truncated-cache indexes (build_streaming store="cache") drop the same
    # rotated tail from the query operand (see neighbors/ivf_pq)
    if index.decoded.shape[-1] < qr_scaled.shape[-1]:
        qr_scaled = qr_scaled[:, :index.decoded.shape[-1]]
    # dense XLA scan off-TPU: the interpreted strip kernel serializes
    # virtual-mesh shards (see distributed/ivf_flat.py)
    interpret = jax.default_backend() != "tpu"
    vals, ids, report = tiled_search(
        qr_scaled, probes, index.lens_max, index.n_lists,
        int(k), index.comms, alpha,
        dense=interpret or not strip_eligible(index.max_list_size),
        interpret=interpret,
        data=index.decoded, ids_arr=index.list_ids, bias=index.bias,
        pair_const=pair_const,
        algo="ivf_pq", n_total=index.n_total, health=health,
    )

    if l2:
        # ‖Rq‖² == ‖q‖² (orthogonal rotation; zero-padding adds nothing)
        vals = jnp.maximum(vals + dist_mod.sqnorm(queries)[:, None], 0.0)
        if index.metric == "euclidean":
            vals = jnp.sqrt(vals)
        vals = jnp.where(ids >= 0, vals, jnp.inf)
    else:
        vals = jnp.where(ids >= 0, -vals, -jnp.inf)
    if index.metric == "cosine":
        vals = jnp.where(ids >= 0, 1.0 - vals, jnp.inf)
    return SearchResult(vals, ids, coverage=report.coverage,
                        degraded=report.degraded,
                        lost_shards=report.dropped)
