"""Multi-device IVF-Flat: globally trained centers, row-sharded lists, one
``shard_map`` search (the raft-dask MNMG model re-expressed as SPMD: one
model per worker, collectives for the merge —
python/raft-dask/raft_dask/common/comms.py:40, docs/source/using_raft_comms.rst;
merge analog knn_merge_parts.cuh:140).

Round-3 redesign (VERDICT.md Missing#2): every stage is a mesh-wide SPMD
program — no host fan-out loops, no per-device ``device_put`` — so the same
code runs multi-host, where only the local shard of each array is
addressable:

  * **build**: the coarse quantizer is trained once with data-sharded
    k-means (psum over shards), so every shard agrees on list ids. Then ONE
    shard_map assigns + spills each shard's rows, a host reduction picks the
    global padded list size, and a second shard_map packs each shard's
    padded lists. Shard arrays are stacked on a leading mesh dimension:
    ``list_data (world, n_lists, mls, dim)`` sharded P(axis).
  * **search**: queries are replicated; the host strip plan is built ONCE
    from the per-list MAX length across shards (every shard runs the same
    grid — the padding this adds over per-shard plans is the shard-to-shard
    length variance, small under random row sharding), and one shard_map
    runs the strip kernel on the local shard + butterfly-merges the (world·k)
    candidates + re-selects. Output is replicated.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import Comms, make_comms
from raft_tpu.core.compat import shard_map
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.trace import traced
from raft_tpu.neighbors import _packing
from raft_tpu.neighbors.ivf_flat import IvfFlatParams
from raft_tpu.ops import distance as dist_mod


@dataclass
class ShardedIvfFlatIndex:
    """Row-sharded IVF-Flat: one coarse quantizer, per-shard padded lists
    stacked on a leading (world,) mesh dimension."""

    centers: jax.Array       # (n_lists, dim) replicated
    list_data: jax.Array     # (world, n_lists, mls, dim) sharded P(axis)
    list_ids: jax.Array      # (world, n_lists, mls) int32, GLOBAL row ids
    # per-entry additive scan bias, built once at build time: ‖x‖² for L2 /
    # 0 for ip-family, +inf at padding (per-call rebuilds were one wasted
    # index-sized pass per search)
    bias: jax.Array          # (world, n_lists, mls) fp32, P(axis)
    metric: str
    n_total: int
    comms: Comms
    lens_max: np.ndarray     # host (n_lists,) max per-list fill across shards

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def max_list_size(self) -> int:
        return self.list_data.shape[2]


@traced("distributed.ivf_flat::build")
def build(
    dataset,
    params: IvfFlatParams = IvfFlatParams(),
    comms: Optional[Comms] = None,
    res: Optional[Resources] = None,
) -> ShardedIvfFlatIndex:
    """Global centers (distributed k-means), then two SPMD phases: assign +
    spill per shard, and pack per shard at a common padded list size."""
    res = res or current_resources()
    comms = comms or make_comms()
    world = comms.size
    axis = comms.axis
    dataset = jnp.asarray(dataset).astype(jnp.float32)
    n, dim = dataset.shape
    if params.n_lists * world > n:
        raise ValueError(f"n_lists={params.n_lists} x {world} shards > n_rows={n}")

    work = dataset
    if params.metric == "cosine":
        work = work / jnp.maximum(jnp.linalg.norm(work, axis=1, keepdims=True), 1e-30)
    km_metric = ("inner_product" if params.metric in ("cosine", "inner_product")
                 else "sqeuclidean")

    # --- global coarse quantizer: data-sharded k-means (psum over shards) --
    from raft_tpu.cluster.kmeans import KMeansParams
    from raft_tpu.distributed import kmeans as dkm

    out, _ = dkm.fit(
        work, KMeansParams(n_clusters=params.n_lists,
                           max_iter=params.kmeans_n_iters, seed=params.seed),
        comms=comms,
    )
    centers = out.centroids
    if params.metric in ("cosine", "inner_product"):
        centers = centers / jnp.maximum(
            jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-30)

    # --- shard rows + SPMD assign/spill phase (shared helpers) -------------
    from raft_tpu.distributed._sharding import (assign_phase, round_mls,
                                                scatter_pack, shard_rows)

    work_sh, gids_sh, rows_per = shard_rows(work, comms)
    group = params.group_size or _packing.auto_group_size(rows_per, params.n_lists)
    cap = params.list_size_cap
    if cap < 0:
        cap = _packing.auto_list_cap(rows_per, params.n_lists, group)
    n_lists = params.n_lists
    labels_sh, counts_np = assign_phase(
        work_sh, gids_sh, centers, km_metric, cap, n_lists, comms)
    mls = round_mls(int(counts_np.max()), group)

    # --- phase 2 (SPMD): pack each shard at the common padded size ---------
    l2 = params.metric in ("sqeuclidean", "euclidean")

    def pack_body(rows, ids, labels):
        rows, ids, labels = rows[0], ids[0], labels[0]
        ld, li = scatter_pack(
            labels,
            [(jnp.zeros((n_lists, mls, rows.shape[1]), rows.dtype), rows),
             (jnp.full((n_lists, mls), -1, jnp.int32), ids)],
            n_lists, mls)
        base = (dist_mod.sqnorm(ld, axis=2) if l2
                else jnp.zeros((n_lists, mls)))
        bias = jnp.where(li >= 0, base, jnp.inf).astype(jnp.float32)
        return ld[None], li[None], bias[None]

    pack_fn = jax.jit(shard_map(
        pack_body, mesh=comms.mesh,
        in_specs=(P(axis, None, None), P(axis, None), P(axis, None)),
        out_specs=(P(axis, None, None, None), P(axis, None, None),
                   P(axis, None, None)),
        check_vma=False,
    ))
    list_data, list_ids, bias = pack_fn(work_sh, gids_sh, labels_sh)
    return ShardedIvfFlatIndex(
        centers, list_data, list_ids, bias,
        params.metric, n, comms, counts_np.max(axis=0).astype(np.int32),
    )


@traced("distributed.ivf_flat::search")
def search(
    index: ShardedIvfFlatIndex,
    queries,
    k: int,
    n_probes: int = 20,
    res: Optional[Resources] = None,
    health=None,
) -> Tuple[jax.Array, jax.Array]:
    """SPMD search: replicated queries, sharded lists, one shard_map per
    query tile. Returns a :class:`~raft_tpu.distributed._sharding.SearchResult`
    — unpacks as global (distances (q, k), row ids (q, k)), replicated on
    every mesh slot, and carries ``coverage``/``degraded`` when shards
    were dropped (``health`` defaults to the process registry)."""
    from raft_tpu.distributed._sharding import SearchResult, tiled_search
    from raft_tpu.neighbors.ivf_flat import _coarse_probes
    from raft_tpu.ops.strip_scan import strip_eligible

    res = res or current_resources()
    queries = jnp.asarray(queries).astype(jnp.float32)
    if queries.shape[1] != index.dim:
        raise ValueError(f"query dim {queries.shape[1]} != index dim {index.dim}")
    if index.metric == "cosine":
        queries = queries / jnp.maximum(
            jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-30)
    n_probes = int(min(n_probes, index.n_lists))
    l2 = index.metric in ("sqeuclidean", "euclidean")

    probes = _coarse_probes(queries, index.centers, n_probes, index.metric,
                            "exact", res.compute_dtype)
    # off-TPU the strip kernel only exists as the single-threaded Pallas
    # interpreter — it serializes the per-shard scans of a virtual mesh and
    # turns weak-scaling numbers into an emulator artifact (ICI r5 finding:
    # brute scaled at 1.0, IVF at 0.6-0.8 purely from this). The dense
    # XLA scan is the honest off-TPU backend.
    interpret = jax.default_backend() != "tpu"
    vals, ids, report = tiled_search(
        queries, probes, index.lens_max, index.n_lists, int(k),
        index.comms, -2.0 if l2 else -1.0,
        dense=interpret or not strip_eligible(index.max_list_size),
        interpret=interpret,
        data=index.list_data, ids_arr=index.list_ids, bias=index.bias,
        algo="ivf_flat", n_total=index.n_total, health=health,
    )
    if l2:
        vals = jnp.maximum(vals + dist_mod.sqnorm(queries)[:, None], 0.0)
        if index.metric == "euclidean":
            vals = jnp.sqrt(vals)
        vals = jnp.where(ids >= 0, vals, jnp.inf)
    elif index.metric == "cosine":
        vals = jnp.where(ids >= 0, 1.0 + vals, jnp.inf)
    else:
        vals = jnp.where(ids >= 0, -vals, -jnp.inf)
    return SearchResult(vals, ids, coverage=report.coverage,
                        degraded=report.degraded,
                        lost_shards=report.dropped)
