"""Multi-device IVF-Flat: globally trained centers, per-device row shards,
cross-shard top-k merge (the raft-dask MNMG model: one model per worker,
collectives for the merge — python/raft-dask/raft_dask/common/comms.py:40,
docs/source/using_raft_comms.rst; merge analog knn_merge_parts.cuh:140).

Architecture. The coarse quantizer is trained ONCE with the data-sharded
k-means (distributed/kmeans.py — psum over shards), so every shard probes
the same lists. Each device then owns a normal :class:`IvfFlatIndex` over
its row range (list ids offset to global row ids) — local list sizes differ
per shard, which is exactly why the reference keeps one index per worker
rather than one sharded container. Search fans the query batch to every
device (XLA dispatches the per-shard searches concurrently), then one
gather + exact re-select merges the (world·k) candidates.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.comms.comms import Comms, make_comms
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.neighbors import ivf_flat as sl  # single-device library
from raft_tpu.neighbors.ivf_flat import IvfFlatIndex, IvfFlatParams


@dataclass
class ShardedIvfFlatIndex:
    """Per-device local indexes sharing one coarse quantizer."""

    shards: List[IvfFlatIndex]   # one per device, list_ids hold GLOBAL rows
    devices: List[jax.Device]
    metric: str
    n_total: int

    @property
    def n_lists(self) -> int:
        return self.shards[0].n_lists

    @property
    def dim(self) -> int:
        return self.shards[0].dim


def build(
    dataset,
    params: IvfFlatParams = IvfFlatParams(),
    comms: Optional[Comms] = None,
    res: Optional[Resources] = None,
) -> ShardedIvfFlatIndex:
    """Train global centers (distributed k-means over the mesh), then build
    each device's local index over its row range."""
    res = res or current_resources()
    comms = comms or make_comms()
    devices = list(comms.mesh.devices.reshape(-1))
    world = len(devices)
    dataset = jnp.asarray(dataset).astype(jnp.float32)
    n, dim = dataset.shape
    if params.n_lists * world > n:
        raise ValueError(
            f"n_lists={params.n_lists} x {world} shards > n_rows={n}")

    # --- global coarse quantizer: data-sharded balanced k-means ------------
    work = dataset
    if params.metric == "cosine":
        work = work / jnp.maximum(
            jnp.linalg.norm(work, axis=1, keepdims=True), 1e-30)
    km_metric = ("inner_product" if params.metric in ("cosine", "inner_product")
                 else "sqeuclidean")
    from raft_tpu.distributed import kmeans as dkm
    from raft_tpu.cluster.kmeans import KMeansParams

    out, _ = dkm.fit(
        work, KMeansParams(n_clusters=params.n_lists,
                           max_iter=params.kmeans_n_iters,
                           seed=params.seed),
        comms=comms,
    )
    centers = out.centroids
    if params.metric in ("cosine", "inner_product"):
        # the data-sharded trainer is plain L2 k-means; restore the spherical
        # invariant the single-device build keeps (IvfFlatIndex docstring:
        # cosine centers are stored L2-normalized)
        centers = centers / jnp.maximum(
            jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-30)

    # --- per-device local indexes over contiguous row ranges ---------------
    from raft_tpu.neighbors import _packing

    bounds = [round(i * n / world) for i in range(world + 1)]
    group = params.group_size or _packing.auto_group_size(
        bounds[1] - bounds[0], params.n_lists)
    shards = []
    for d, dev in enumerate(devices):
        lo, hi = bounds[d], bounds[d + 1]
        rows = work[lo:hi]
        labels = kmeans_balanced.predict(
            rows, centers, kmeans_balanced.KMeansBalancedParams(metric=km_metric),
            res=res,
        )
        cap = params.list_size_cap
        if cap < 0:
            cap = _packing.auto_list_cap(hi - lo, params.n_lists, group)
        if cap:
            labels = _packing.spill_to_cap(rows, centers, labels, km_metric, cap)
        list_data, list_ids = sl._pack_lists(rows,
                                             jnp.arange(lo, hi, dtype=jnp.int32),
                                             labels, params.n_lists, group)
        list_norms = None
        if params.metric in ("sqeuclidean", "euclidean"):
            from raft_tpu.ops import distance as dist_mod

            list_norms = dist_mod.sqnorm(list_data, axis=2)
        local = IvfFlatIndex(centers, list_data, list_ids, list_norms,
                             params.metric)
        shards.append(jax.device_put(local, dev))
    return ShardedIvfFlatIndex(shards, devices, params.metric, n)


def search(
    index: ShardedIvfFlatIndex,
    queries,
    k: int,
    n_probes: int = 20,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Fan out, search every shard, merge the (world·k) candidates exactly.
    Returns global (distances (q, k), row ids (q, k))."""
    res = res or current_resources()
    queries = jnp.asarray(queries).astype(jnp.float32)
    parts = []
    for shard, dev in zip(index.shards, index.devices):
        q_dev = jax.device_put(queries, dev)
        parts.append(sl.search(shard, q_dev, k, n_probes=n_probes, res=res))
    # merge on the first device (knn_merge_parts analog)
    vals = jnp.concatenate([jax.device_put(v, index.devices[0]) for v, _ in parts], axis=1)
    ids = jnp.concatenate([jax.device_put(i, index.devices[0]) for _, i in parts], axis=1)
    select_min = index.metric != "inner_product"
    key = vals if select_min else -vals
    key = jnp.where(ids >= 0, key, jnp.inf)
    top, sel = jax.lax.top_k(-key, k)
    out_i = jnp.take_along_axis(ids, sel, axis=1)
    out_v = jnp.take_along_axis(vals, sel, axis=1)
    return out_v, out_i
