"""Multi-node-multi-chip (MNMG) algorithms over the comms layer.

Reference analog: the SPMD rank-per-GPU pattern taught in
docs/source/using_raft_comms.rst and consumed by cuML/cuGraph — each rank
holds a data shard, algorithms combine local compute with ``comms_t``
collectives (SURVEY.md §2.9.3). Here each *mesh slot* holds a shard and the
collectives are XLA collectives over ICI/DCN, issued from ``shard_map``
library code (not demo code): sharded exact kNN with cross-shard top-k merge,
data-sharded k-means, and multi-device IVF-Flat (global quantizer + local
per-device indexes, the raft-dask one-model-per-worker architecture).
"""

from raft_tpu.distributed import (brute_force, cagra, ivf_bq, ivf_flat,
                                  ivf_pq, kmeans)
from raft_tpu.distributed import snapshot
from raft_tpu.distributed._sharding import SearchResult, ShardReport, probe_shards

__all__ = ["SearchResult", "ShardReport", "brute_force", "cagra", "ivf_bq",
           "ivf_flat", "ivf_pq", "kmeans", "probe_shards", "snapshot"]
