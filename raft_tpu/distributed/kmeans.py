"""Data-sharded k-means: the MNMG Lloyd loop over psum collectives.

Reference analog: the comms pattern cuML's MNMG KMeans builds on raft's
``comms_t`` (docs/source/using_raft_comms.rst — per-rank local labeling +
``allreduce`` of per-cluster sums/counts), with the single-device EM semantics
of cluster/kmeans.cuh:88/617 (fused distance+argmin assignment, weighted
update, empty clusters keep their center, relative-tol inertia stopping).

TPU design: ONE ``shard_map`` region containing the whole ``while_loop`` —
each EM iteration is a shard-local fused_l2_nn_argmin plus two ``psum``s
(cluster sums, cluster counts), so the entire fit compiles to a single XLA
program with ICI collectives inside the loop body.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from raft_tpu import obs

from raft_tpu.cluster.kmeans import (
    KMeansOutput,
    KMeansParams,
    _init_plus_plus,
    _init_random,
)
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.comms.comms import Comms, make_comms, shard_padded
from raft_tpu.core.compat import shard_map
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.trace import traced
from raft_tpu.ops.distance import fused_l2_nn_argmin


@functools.lru_cache(maxsize=32)
def _make_fit_fn(mesh, axis, n_clusters, max_iter, tol):
    def spmd_fit(shard_X, shard_w, centers0):
        def em_step(centers):
            d2, labels = fused_l2_nn_argmin(shard_X, centers)
            onehot = jax.nn.one_hot(labels, n_clusters, dtype=jnp.float32)
            w = shard_w[:, None]
            sums = lax.psum(onehot.T @ (shard_X * w), axis)
            counts = lax.psum(onehot.T @ w, axis)[:, 0]
            safe = jnp.maximum(counts, 1e-12)[:, None]
            new_centers = jnp.where(counts[:, None] > 0, sums / safe, centers)
            inertia = lax.psum(jnp.sum(d2 * shard_w), axis)
            return new_centers, inertia

        def cond(carry):
            _, inertia, prev, it = carry
            return jnp.logical_and(it < max_iter, inertia < prev * (1.0 - tol))

        def body(carry):
            centers, inertia, _, it = carry
            nc, ni = em_step(centers)
            return nc, ni, inertia, it + 1

        c1, i1 = em_step(centers0)
        centers, inertia, _, n_iter = lax.while_loop(
            cond, body, (c1, i1, jnp.float32(jnp.inf), jnp.int32(1))
        )
        d2, labels = fused_l2_nn_argmin(shard_X, centers)
        inertia = lax.psum(jnp.sum(d2 * shard_w), axis)
        return centers, inertia, n_iter, labels

    fn = shard_map(
        spmd_fit,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis), P()),
        out_specs=(P(), P(), P(), P(axis)),
        check_vma=False,
    )
    return jax.jit(fn)


def _seed_centers(kinit, X, weights, params: KMeansParams, centroids):
    """Initial centers, honoring ``params.init`` like single-device fit.

    kmeans++ runs on a bounded weighted random subsample (the reference
    trains coarse centers on a sampled trainset for the same scalability
    reason, ivf_flat_types.hpp:55 kmeans_trainset_fraction); the subsample is
    replicated — O(max(4k, 2048)·dim) — while the full X stays sharded.
    """
    k = params.n_clusters
    n = X.shape[0]
    if params.init == "array":
        if centroids is None:
            raise ValueError('init="array" requires centroids')
        return jnp.asarray(centroids)
    if params.init == "random":
        return _init_random(kinit, X, k)
    ks, kpp = jax.random.split(kinit)
    n_sample = min(n, max(4 * k, 2048))
    rows = jax.random.choice(ks, n, (n_sample,), replace=False)
    return _init_plus_plus(kpp, jnp.asarray(X[rows]), weights[rows], k)


@traced("distributed.kmeans::fit")
def fit(
    X,
    params: KMeansParams = KMeansParams(),
    sample_weight=None,
    centroids=None,
    comms: Optional[Comms] = None,
    res: Optional[Resources] = None,
) -> Tuple[KMeansOutput, jax.Array]:
    """Distributed k-means fit; returns ``(KMeansOutput, labels)``.

    Mirrors ``cluster.kmeans.fit`` semantics (params.seed/init/n_init all
    honored; ``centroids`` seeds ``init="array"``), with ``X`` padded to a
    multiple of the communicator size and row-sharded (padding rows get
    weight 0 so they never influence centers or inertia).
    """
    res = res or current_resources()
    comms = comms or make_comms(res)
    X = jnp.asarray(X, jnp.float32)
    n = X.shape[0]
    k = params.n_clusters
    if not 0 < k <= n:
        raise ValueError(f"n_clusters={k} out of range for n={n}")

    w = (
        jnp.ones((n,), jnp.float32)
        if sample_weight is None
        else jnp.asarray(sample_weight, jnp.float32)
    )
    Xs, _ = shard_padded(X, comms)
    ws, _ = shard_padded(w, comms, fill=0.0)
    fn = _make_fit_fn(
        comms.mesh, comms.axis, int(k), int(params.max_iter), float(params.tol)
    )

    key = jax.random.key(params.seed)
    best = None
    best_labels = None
    for _ in range(max(1, params.n_init)):
        kinit, key = jax.random.split(key)
        centers0 = _seed_centers(kinit, X, w, params, centroids)
        centers, inertia, n_iter, labels = fn(Xs, ws, centers0)
        out = KMeansOutput(centers, inertia, n_iter)
        if best is None or float(out.inertia) < float(best.inertia):
            best, best_labels = out, labels
        if params.init == "array":
            break  # deterministic start: n_init re-runs would be identical
    return best, best_labels[:n]


# ---------------------------------------------------------------------------
# Balanced k-means — the distributed IVF coarse-quantizer trainer
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=32)
def _make_balanced_fit_fn(mesh, axis, n_clusters, n_iters, metric,
                          threshold):
    """One shard_map'd program: the whole balanced EM as a while_loop of
    shard-local assigns + two ``psum``s (cluster sums, counts) — the
    O(N·d·K) assignment phase is SPMD, which is the entire point of
    training the coarse codebook distributed (billion-scale builds pay
    kmeans, not encode).

    The balancing reseed (cluster/kmeans_balanced.cuh adjust_centers
    analog, splitting form — see cluster/kmeans_balanced._balanced_em) is
    made SPMD by electing a GLOBAL random representative per cluster:
    per-row uniform keys (folded with the shard index so shards draw
    distinct keys), shard-local segment_max, cross-shard ``pmax``, and a
    masked ``psum`` to fetch the winning row — deterministic given the
    seed, no host sync, ties (measure-zero fp uniforms) fold to the
    representatives' mean."""

    def spmd_fit(shard_X, shard_w, centers0, key):
        rp = shard_X.shape[0]
        me = lax.axis_index(axis)
        n_global = lax.psum(jnp.sum(shard_w), axis)
        average = n_global / n_clusters
        max_iters = 5 * n_iters

        def assign(centers):
            if metric == "inner_product":
                ip = lax.dot_general(
                    shard_X, centers, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                return -jnp.max(ip, axis=1), \
                    jnp.argmax(ip, axis=1).astype(jnp.int32)
            return fused_l2_nn_argmin(shard_X, centers)

        def m_step(labels, centers):
            """Weighted cross-shard centroid update — the ONE copy the
            loop body and the final step share; returns (raw centers,
            global counts). The ip renormalize (:func:`renorm`) applies
            AFTER any reseed, so reseeded centers are normalized too."""
            onehot = jax.nn.one_hot(labels, n_clusters, dtype=jnp.float32)
            w = shard_w[:, None]
            sums = lax.psum(onehot.T @ (shard_X * w), axis)
            counts = lax.psum((onehot * w).sum(axis=0), axis)
            centers = jnp.where(counts[:, None] > 0,
                                sums / jnp.maximum(counts, 1e-12)[:, None],
                                centers)
            return centers, counts

        def renorm(centers):
            # IP/cosine EM drifts toward zero centers without
            # renormalization (detail/kmeans_balanced.cuh:656-668)
            if metric != "inner_product":
                return centers
            return centers / jnp.maximum(
                jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-30)

        def step(it, centers):
            _, labels = assign(centers)
            centers, counts = m_step(labels, centers)
            small = counts < threshold * average
            # global random representative per cluster (docstring)
            u = jax.random.uniform(
                jax.random.fold_in(jax.random.fold_in(key, it), me),
                (rp,)) * shard_w
            maxu_l = jax.ops.segment_max(u, labels,
                                         num_segments=n_clusters)
            maxu = lax.pmax(jnp.maximum(maxu_l, 0.0), axis)
            is_rep = ((u >= maxu[labels]) & (u > 0)).astype(jnp.float32)
            rep_sum = lax.psum(
                jax.ops.segment_sum(shard_X * is_rep[:, None], labels,
                                    num_segments=n_clusters), axis)
            rep_cnt = lax.psum(
                jax.ops.segment_sum(is_rep, labels,
                                    num_segments=n_clusters), axis)
            rep_pt = rep_sum / jnp.maximum(rep_cnt, 1.0)[:, None]
            donor_order = jnp.argsort(-counts)
            rank = jnp.clip(jnp.cumsum(small.astype(jnp.int32)) - 1, 0,
                            n_clusters - 1)
            donor = donor_order[rank]
            c_new = 0.5 * (centers[donor] + rep_pt[donor])
            reseed = small & (rep_cnt[donor] > 0)
            centers = jnp.where(reseed[:, None], c_new, centers)
            return renorm(centers), jnp.any(small)

        def cond(carry):
            _, it, rebalancing = carry
            return jnp.logical_or(
                it < n_iters,
                jnp.logical_and(rebalancing, it < max_iters))

        def body(carry):
            centers, it, _ = carry
            centers, rebalancing = step(it, centers)
            return centers, it + 1, rebalancing

        centers, _, _ = lax.while_loop(
            cond, body, (centers0, jnp.int32(0), jnp.bool_(True)))
        # final M step + re-predict so returned labels match returned
        # centers (the single-device _balanced_em contract)
        _, labels = assign(centers)
        centers, _ = m_step(labels, centers)
        centers = renorm(centers)
        score, labels = assign(centers)
        inertia = lax.psum(jnp.sum(score * shard_w), axis)
        return centers, labels, inertia

    fn = shard_map(
        spmd_fit,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis), P(), P()),
        out_specs=(P(), P(axis), P()),
        check_vma=False,
    )
    return jax.jit(fn)


@traced("distributed.kmeans::fit_balanced")
def fit_balanced(
    X,
    n_clusters: int,
    params: KMeansBalancedParams = KMeansBalancedParams(),
    comms: Optional[Comms] = None,
    res: Optional[Resources] = None,
    health=None,
):
    """Data-sharded balanced k-means — the distributed IVF coarse trainer
    (the ``kmeans_balanced.fit_predict`` analog over the mesh; ivf_bq's
    distributed build consumes it so the only O(N·d·K) build phase is
    SPMD). Returns ``(centers, labels, report)`` where ``report`` is the
    shard-health :class:`~raft_tpu.distributed._sharding.ShardReport`.

    Behind the shard-health gate like the five distributed searches: the
    dispatch runs through ``probe_shards(..., phase="fit")`` (faultpoint
    ``distributed.kmeans.fit.shard``) first, and a failing shard's rows
    get weight 0 in every ``psum`` — training proceeds over the
    survivors, coverage reported, never a crash. Labels are still
    computed for every row (the program is SPMD; a masked shard's rows
    simply never influenced the centers)."""
    from raft_tpu.distributed._sharding import probe_shards

    res = res or current_resources()
    comms = comms or make_comms(res)
    X = jnp.asarray(X, jnp.float32)
    n = X.shape[0]
    if not 0 < n_clusters <= n:
        raise ValueError(f"n_clusters={n_clusters} out of range for n={n}")
    world = comms.size
    report = probe_shards("kmeans", world, n, health, phase="fit")
    w = np.ones(n, np.float32)
    rows_per = -(-n // world)
    for r in range(world):
        if not report.ok[r]:
            w[r * rows_per:(r + 1) * rows_per] = 0.0
    Xs, _ = shard_padded(X, comms)
    ws, _ = shard_padded(jnp.asarray(w), comms, fill=0.0)
    fn = _make_balanced_fit_fn(
        comms.mesh, comms.axis, int(n_clusters), int(params.n_iters),
        params.metric, float(params.balancing_threshold))
    key = jax.random.key(params.seed)
    k_init, k_adjust = jax.random.split(key)
    rows = jax.random.randint(k_init, (n_clusters,), 0, n)
    centers0 = X[rows].astype(jnp.float32)
    if obs.enabled():
        obs.add("distributed.kmeans.fit_balanced.rows", n)
        obs.add("distributed.kmeans.fit_balanced.clusters", int(n_clusters))
    centers, labels, _ = fn(Xs, ws, centers0, k_adjust)
    return centers, labels[:n], report
