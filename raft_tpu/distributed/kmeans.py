"""Data-sharded k-means: the MNMG Lloyd loop over psum collectives.

Reference analog: the comms pattern cuML's MNMG KMeans builds on raft's
``comms_t`` (docs/source/using_raft_comms.rst — per-rank local labeling +
``allreduce`` of per-cluster sums/counts), with the single-device EM semantics
of cluster/kmeans.cuh:88/617 (fused distance+argmin assignment, weighted
update, empty clusters keep their center, relative-tol inertia stopping).

TPU design: ONE ``shard_map`` region containing the whole ``while_loop`` —
each EM iteration is a shard-local fused_l2_nn_argmin plus two ``psum``s
(cluster sums, cluster counts), so the entire fit compiles to a single XLA
program with ICI collectives inside the loop body.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from raft_tpu.cluster.kmeans import (
    KMeansOutput,
    KMeansParams,
    _init_plus_plus,
    _init_random,
)
from raft_tpu.comms.comms import Comms, make_comms, shard_padded
from raft_tpu.core.compat import shard_map
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.trace import traced
from raft_tpu.ops.distance import fused_l2_nn_argmin


@functools.lru_cache(maxsize=32)
def _make_fit_fn(mesh, axis, n_clusters, max_iter, tol):
    def spmd_fit(shard_X, shard_w, centers0):
        def em_step(centers):
            d2, labels = fused_l2_nn_argmin(shard_X, centers)
            onehot = jax.nn.one_hot(labels, n_clusters, dtype=jnp.float32)
            w = shard_w[:, None]
            sums = lax.psum(onehot.T @ (shard_X * w), axis)
            counts = lax.psum(onehot.T @ w, axis)[:, 0]
            safe = jnp.maximum(counts, 1e-12)[:, None]
            new_centers = jnp.where(counts[:, None] > 0, sums / safe, centers)
            inertia = lax.psum(jnp.sum(d2 * shard_w), axis)
            return new_centers, inertia

        def cond(carry):
            _, inertia, prev, it = carry
            return jnp.logical_and(it < max_iter, inertia < prev * (1.0 - tol))

        def body(carry):
            centers, inertia, _, it = carry
            nc, ni = em_step(centers)
            return nc, ni, inertia, it + 1

        c1, i1 = em_step(centers0)
        centers, inertia, _, n_iter = lax.while_loop(
            cond, body, (c1, i1, jnp.float32(jnp.inf), jnp.int32(1))
        )
        d2, labels = fused_l2_nn_argmin(shard_X, centers)
        inertia = lax.psum(jnp.sum(d2 * shard_w), axis)
        return centers, inertia, n_iter, labels

    fn = shard_map(
        spmd_fit,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis), P()),
        out_specs=(P(), P(), P(), P(axis)),
        check_vma=False,
    )
    return jax.jit(fn)


def _seed_centers(kinit, X, weights, params: KMeansParams, centroids):
    """Initial centers, honoring ``params.init`` like single-device fit.

    kmeans++ runs on a bounded weighted random subsample (the reference
    trains coarse centers on a sampled trainset for the same scalability
    reason, ivf_flat_types.hpp:55 kmeans_trainset_fraction); the subsample is
    replicated — O(max(4k, 2048)·dim) — while the full X stays sharded.
    """
    k = params.n_clusters
    n = X.shape[0]
    if params.init == "array":
        if centroids is None:
            raise ValueError('init="array" requires centroids')
        return jnp.asarray(centroids)
    if params.init == "random":
        return _init_random(kinit, X, k)
    ks, kpp = jax.random.split(kinit)
    n_sample = min(n, max(4 * k, 2048))
    rows = jax.random.choice(ks, n, (n_sample,), replace=False)
    return _init_plus_plus(kpp, jnp.asarray(X[rows]), weights[rows], k)


@traced("distributed.kmeans::fit")
def fit(
    X,
    params: KMeansParams = KMeansParams(),
    sample_weight=None,
    centroids=None,
    comms: Optional[Comms] = None,
    res: Optional[Resources] = None,
) -> Tuple[KMeansOutput, jax.Array]:
    """Distributed k-means fit; returns ``(KMeansOutput, labels)``.

    Mirrors ``cluster.kmeans.fit`` semantics (params.seed/init/n_init all
    honored; ``centroids`` seeds ``init="array"``), with ``X`` padded to a
    multiple of the communicator size and row-sharded (padding rows get
    weight 0 so they never influence centers or inertia).
    """
    res = res or current_resources()
    comms = comms or make_comms(res)
    X = jnp.asarray(X, jnp.float32)
    n = X.shape[0]
    k = params.n_clusters
    if not 0 < k <= n:
        raise ValueError(f"n_clusters={k} out of range for n={n}")

    w = (
        jnp.ones((n,), jnp.float32)
        if sample_weight is None
        else jnp.asarray(sample_weight, jnp.float32)
    )
    Xs, _ = shard_padded(X, comms)
    ws, _ = shard_padded(w, comms, fill=0.0)
    fn = _make_fit_fn(
        comms.mesh, comms.axis, int(k), int(params.max_iter), float(params.tol)
    )

    key = jax.random.key(params.seed)
    best = None
    best_labels = None
    for _ in range(max(1, params.n_init)):
        kinit, key = jax.random.split(key)
        centers0 = _seed_centers(kinit, X, w, params, centroids)
        centers, inertia, n_iter, labels = fn(Xs, ws, centers0)
        out = KMeansOutput(centers, inertia, n_iter)
        if best is None or float(out.inertia) < float(best.inertia):
            best, best_labels = out, labels
        if params.init == "array":
            break  # deterministic start: n_init re-runs would be identical
    return best, best_labels[:n]
