"""Sharded-index snapshots: per-shard containers + a fleet manifest.

The persistence half of ROADMAP item 4's resilience sub-goal: when a shard
goes LOST mid-serving (resilience/shard_health.py), the recovery action
must be *reload from snapshot*, not rebuild — a 1M-row IVF-PQ build is
minutes of k-means while a shard reload is one file read + device_put.

Snapshot directory layout (all files v2 crash-safe containers —
core/serialize.py: atomic writes, per-array CRC32s)::

    MANIFEST.json        the commit point, written LAST (atomic): kind,
                         world, n_total, file list, which arrays exist
    common.raft          replicated quantizers + host-side tables
    shard_0000.raft ...  one file per shard with THAT shard's slice of
                         every mesh-sharded array

A snapshot is valid iff its manifest parses — a crash mid-snapshot leaves
either the previous complete snapshot or shard files with no manifest,
never a half-readable one. Per-shard files (not one blob) are the point:
restoring shard 3 reads ``shard_0003.raft`` only, and on a real multi-host
pod each process snapshots just its addressable shards (this
single-process virtual-mesh version writes all of them, the same division
of labor as distributed/cagra.py's build loop).

Covers all four distributed index types; the ``kind`` in the manifest is
validated on load, like every single-device container.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs, resilience
from raft_tpu.comms.comms import Comms, make_comms
from raft_tpu.core.fsio import atomic_write
from raft_tpu.core.serialize import load_arrays, save_arrays

MANIFEST = "MANIFEST.json"
_MANIFEST_VERSION = 1


@dataclass(frozen=True)
class _Spec:
    """What to persist for one distributed index type."""

    stacked: bool        # True: arrays carry a leading (world,) mesh dim;
    #                      False: row-sharded over dim 0 (brute force)
    sharded: Tuple[str, ...]     # mesh-sharded array attrs (optional ok)
    replicated: Tuple[str, ...]  # replicated device-array attrs
    host: Tuple[str, ...]        # host numpy attrs (lens_max)
    meta: Tuple[str, ...]        # scalar attrs


_SPECS = {
    "brute_force": _Spec(False, ("dataset", "norms"), (), (),
                         ("metric", "metric_arg", "n_total")),
    "ivf_flat": _Spec(True, ("list_data", "list_ids", "bias"), ("centers",),
                      ("lens_max",), ("metric", "n_total")),
    "ivf_pq": _Spec(True, ("list_codes", "list_ids", "bias", "decoded"),
                    ("centers", "rotation", "codebooks"), ("lens_max",),
                    ("decoded_scale", "metric", "pq_bits", "n_total")),
    "cagra": _Spec(True, ("dataset", "graph", "proj", "code_scale",
                          "nbr_codes", "centroids", "centroid_reps",
                          "proj_energy"), (), (), ("n_total",)),
}


def _kind_of(index) -> str:
    from raft_tpu.distributed.brute_force import ShardedBruteForceIndex
    from raft_tpu.distributed.cagra import ShardedCagraIndex
    from raft_tpu.distributed.ivf_flat import ShardedIvfFlatIndex
    from raft_tpu.distributed.ivf_pq import ShardedIvfPqIndex

    table = {ShardedBruteForceIndex: "brute_force",
             ShardedIvfFlatIndex: "ivf_flat",
             ShardedIvfPqIndex: "ivf_pq",
             ShardedCagraIndex: "cagra"}
    for cls, kind in table.items():
        if isinstance(index, cls):
            return kind
    raise ValueError(f"not a distributed index: {type(index).__name__}")


def _index_cls(kind: str):
    from raft_tpu.distributed import brute_force, cagra, ivf_flat, ivf_pq

    return {"brute_force": brute_force.ShardedBruteForceIndex,
            "ivf_flat": ivf_flat.ShardedIvfFlatIndex,
            "ivf_pq": ivf_pq.ShardedIvfPqIndex,
            "cagra": cagra.ShardedCagraIndex}[kind]


def _shard_file(r: int) -> str:
    return f"shard_{r:04d}.raft"


def _shard_slice(arr: np.ndarray, r: int, world: int, stacked: bool):
    if stacked:
        return arr[r]
    rows_per = arr.shape[0] // world
    return arr[r * rows_per:(r + 1) * rows_per]


def _put_sharded(arr: np.ndarray, comms: Comms):
    spec = (comms.axis,) + (None,) * (arr.ndim - 1)
    return jax.device_put(jnp.asarray(arr), comms.sharding(*spec))


def save(index, directory) -> str:
    """Snapshot a distributed index into ``directory``; returns the
    manifest path. Every file is written atomically; the manifest lands
    last, so a killed snapshot never shadows the previous complete one."""
    kind = _kind_of(index)
    spec = _SPECS[kind]
    world = index.comms.size
    directory = os.fspath(directory)
    os.makedirs(directory, exist_ok=True)
    present = [n for n in spec.sharded if getattr(index, n) is not None]
    attrs = None
    if obs.enabled():
        obs.add("distributed.snapshot.saves")
        attrs = {"shard": world}
    with obs.record_span("distributed.snapshot::save", attrs=attrs):
        common = {n: getattr(index, n) for n in spec.replicated}
        common.update({n: np.asarray(getattr(index, n)) for n in spec.host})
        meta = {"kind": kind, "snapshot": "common",
                **{n: getattr(index, n) for n in spec.meta}}
        save_arrays(os.path.join(directory, "common.raft"), meta, common)
        # host copies of the sharded arrays once, sliced per shard below
        # (single-process virtual mesh: everything is addressable)
        host_arrays = {n: np.asarray(getattr(index, n)) for n in present}
        for r in range(world):
            save_arrays(
                os.path.join(directory, _shard_file(r)),
                {"kind": kind, "snapshot": "shard", "shard": r,
                 "world": world},
                {n: _shard_slice(host_arrays[n], r, world, spec.stacked)
                 for n in present})
        manifest = {
            "version": _MANIFEST_VERSION,
            "kind": kind,
            "world": world,
            "n_total": int(index.n_total),
            "common": "common.raft",
            "shards": [_shard_file(r) for r in range(world)],
            "sharded_arrays": present,
        }
        mpath = os.path.join(directory, MANIFEST)
        with atomic_write(mpath, "w") as f:
            json.dump(manifest, f, indent=2)
    return mpath


def read_manifest(directory) -> dict:
    """Parse and sanity-check a snapshot manifest."""
    path = os.path.join(os.fspath(directory), MANIFEST)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no snapshot manifest at {path} — the snapshot was never "
            f"committed (or the directory is wrong)")
    with open(path) as f:
        manifest = json.load(f)
    if manifest.get("version", 0) > _MANIFEST_VERSION:
        raise ValueError(
            f"unsupported snapshot manifest version {manifest.get('version')}")
    if manifest.get("kind") not in _SPECS:
        raise ValueError(
            f"snapshot manifest names unknown index kind "
            f"{manifest.get('kind')!r}")
    return manifest


def _load_shard_arrays(directory, manifest, r: int, kind: str) -> dict:
    meta, arrays = load_arrays(
        os.path.join(os.fspath(directory), manifest["shards"][r]))
    if meta.get("kind") != kind or meta.get("shard") != r:
        raise ValueError(
            f"snapshot shard file {manifest['shards'][r]} is for "
            f"kind={meta.get('kind')!r} shard={meta.get('shard')!r}, "
            f"expected kind={kind!r} shard={r}")
    return arrays


def load(directory, comms: Optional[Comms] = None):
    """Rebuild a distributed index from a snapshot directory (the
    inverse of :func:`save`): replicated arrays from ``common.raft``,
    per-shard slices reassembled and re-placed over ``comms``."""
    manifest = read_manifest(directory)
    kind = manifest["kind"]
    spec = _SPECS[kind]
    comms = comms or make_comms()
    if comms.size != manifest["world"]:
        raise ValueError(
            f"snapshot was taken over world={manifest['world']} but the "
            f"communicator has {comms.size} slots — resharding is not "
            f"supported; rebuild instead")
    attrs = None
    if obs.enabled():
        obs.add("distributed.snapshot.loads")
        attrs = {"shard": int(manifest["world"])}
    with obs.record_span("distributed.snapshot::load", attrs=attrs):
        meta, common = load_arrays(
            os.path.join(os.fspath(directory), manifest["common"]))
        if meta.get("kind") != kind:
            raise ValueError(
                f"snapshot common file is for kind={meta.get('kind')!r}, "
                f"manifest says {kind!r}")
        kwargs = {n: meta[n] for n in spec.meta}
        kwargs.update({n: jnp.asarray(common[n]) for n in spec.replicated})
        kwargs.update({n: np.asarray(common[n]) for n in spec.host})
        present = manifest.get("sharded_arrays", list(spec.sharded))
        for n in spec.sharded:
            if n not in present:
                kwargs[n] = None  # optional array the build never produced
        parts = {n: [] for n in present}
        for r in range(manifest["world"]):
            arrays = _load_shard_arrays(directory, manifest, r, kind)
            for n in present:
                parts[n].append(arrays[n])
        for n in present:
            full = (np.stack(parts[n]) if spec.stacked
                    else np.concatenate(parts[n], axis=0))
            kwargs[n] = _put_sharded(full, comms)
        return _index_cls(kind)(comms=comms, **kwargs)


def restore_shard(index, directory, shard: int):
    """Reload ONE shard's slice of every sharded array from its snapshot
    file and return a new index with that slice replaced — the recovery
    action for a LOST shard. Reads only ``shard_<r>.raft`` (+ manifest)."""
    kind = _kind_of(index)
    spec = _SPECS[kind]
    manifest = read_manifest(directory)
    if manifest["kind"] != kind:
        raise ValueError(
            f"snapshot at {os.fspath(directory)} holds a "
            f"{manifest['kind']!r} index, not {kind!r}")
    world = index.comms.size
    if manifest["world"] != world:
        raise ValueError(
            f"snapshot world {manifest['world']} != index world {world}")
    shard = int(shard)
    if not 0 <= shard < world:
        raise ValueError(f"shard {shard} out of range for world {world}")
    attrs = None
    if obs.enabled():
        obs.add("distributed.snapshot.shard_restores")
        attrs = {"shard": shard}
    with obs.record_span("distributed.snapshot::restore_shard", attrs=attrs):
        arrays = _load_shard_arrays(directory, manifest, shard, kind)
        updates = {}
        for n in manifest.get("sharded_arrays", list(spec.sharded)):
            cur = getattr(index, n)
            if cur is None:
                continue
            host = np.asarray(cur)
            if spec.stacked:
                host = host.copy()
                host[shard] = arrays[n]
            else:
                rows_per = host.shape[0] // world
                host = host.copy()
                host[shard * rows_per:(shard + 1) * rows_per] = arrays[n]
            updates[n] = _put_sharded(host, index.comms)
        return dataclasses.replace(index, **updates)


def recover(index, directory,
            health: Optional[resilience.ShardHealth] = None):
    """Reload every LOST shard from the snapshot and reinstate it in the
    health registry. Returns ``(index, recovered_shards)`` — the degraded
    loop's exit: search again and coverage is back to 1.0."""
    health = health or resilience.shard_health()
    recovered = []
    for shard in health.lost():
        index = restore_shard(index, directory, shard)
        health.mark_recovered(shard)
        recovered.append(shard)
    return index, tuple(recovered)
