"""Multi-device IVF-BQ: globally trained quantizers, row-sharded 1-bit
code lists, one ``shard_map`` search riding the shard-health gate.

The raft-dask MNMG division of labor (see distributed/ivf_flat.py):

  * **Global, replicated**: coarse centers (data-sharded k-means, psum over
    shards) and the random rotation — BQ has no codebooks, so the entire
    replicated quantizer state is one (rot_dim, rot_dim) matrix.
  * **Per shard**: its rows' packed sign codes, ids, and the two
    correction-scalar planes (scale f, additive bias) — encoded in ONE
    SPMD pass through the same ``_encode_chunk`` the single-host build
    uses, so the estimator cannot drift between flows.
  * **Search**: identical scan plan on every shard (per-list MAX fill),
    local packed scan (``scan="bq"`` through the shared tiled_search —
    strip kernel on TPU, probe-tiled dense unpack off-TPU), butterfly
    candidate merge, and the degraded-mode dispatch gate: a LOST shard
    costs coverage, never the query (``SearchResult.coverage``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import Comms, make_comms
from raft_tpu.core.compat import shard_map
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.trace import traced
from raft_tpu.neighbors import _packing
from raft_tpu.neighbors import ivf_bq as sl
from raft_tpu.neighbors.ivf_bq import IvfBqParams
from raft_tpu.ops import distance as dist_mod
from raft_tpu.ops import linalg


@dataclass
class ShardedIvfBqIndex:
    """Row-sharded IVF-BQ: replicated centers + rotation, per-shard packed
    code lists and correction planes stacked on a leading (world,) mesh
    dimension."""

    centers: jax.Array     # (n_lists, dim) replicated
    rotation: jax.Array    # (rot_dim, rot_dim) dense | (rot_dim,) signs
    list_codes: jax.Array  # (world, n_lists, mls, bits·rot_dim/8), P(axis)
    list_ids: jax.Array    # (world, n_lists, mls) int32, GLOBAL row ids
    list_scale: jax.Array  # (world, n_lists, mls) fp32, P(axis)
    bias: jax.Array        # (world, n_lists, mls) fp32, +inf padding
    metric: str
    n_total: int
    comms: Comms
    lens_max: np.ndarray   # host (n_lists,) max per-list fill across shards
    bits: int = 1
    rotation_kind: str = "dense"

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def max_list_size(self) -> int:
        return self.list_codes.shape[2]


@traced("distributed.ivf_bq::build")
def build(
    dataset,
    params: IvfBqParams = IvfBqParams(),
    comms: Optional[Comms] = None,
    res: Optional[Resources] = None,
) -> ShardedIvfBqIndex:
    """Global centers (distributed balanced k-means — the shard-mapped
    assign + psum centroid scatter-reduce that makes the build's only
    O(N·d·K) phase SPMD, behind the shard-health gate) + replicated
    rotation, then two SPMD phases: assign + spill per shard, level-encode
    + pack per shard at a common padded list size."""
    res = res or current_resources()
    comms = comms or make_comms()
    world = comms.size
    axis = comms.axis
    dataset = jnp.asarray(dataset).astype(jnp.float32)
    n, dim = dataset.shape
    if params.n_lists * world > n:
        raise ValueError(f"n_lists={params.n_lists} x {world} shards > n_rows={n}")
    rot_dim = sl.auto_rot_dim(dim, params.rotation_kind)
    nb = (params.bits * rot_dim) // 8

    work = dataset
    if params.metric == "cosine":
        work = work / jnp.maximum(jnp.linalg.norm(work, axis=1, keepdims=True), 1e-30)
    km_metric = ("inner_product" if params.metric in ("cosine", "inner_product")
                 else "sqeuclidean")

    # --- global coarse quantizer: data-sharded BALANCED k-means (psum over
    # shards, behind the shard-health fit gate — distributed/kmeans) ------
    from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
    from raft_tpu.distributed import kmeans as dkm

    centers, _, _ = dkm.fit_balanced(
        work, params.n_lists,
        KMeansBalancedParams(n_iters=params.kmeans_n_iters,
                             metric=km_metric, seed=params.seed),
        comms=comms,
    )
    # replicated rotation: every shard derives the identical operand from
    # the shared seed — no collective
    key = jax.random.key(params.seed)
    _, k_rot = jax.random.split(key)
    rotation = sl._make_rotation(k_rot, rot_dim, params.rotation_kind)

    # --- shard rows + SPMD assign/spill phase (shared helpers) -------------
    from raft_tpu.distributed._sharding import (assign_phase, round_mls,
                                                scatter_pack, shard_rows)

    work_sh, gids_sh, rows_per = shard_rows(work, comms)
    cap = params.list_size_cap
    if cap < 0:
        cap = _packing.auto_list_cap(rows_per, params.n_lists, sl._GROUP)
    n_lists = params.n_lists
    labels_sh, counts_np = assign_phase(
        work_sh, gids_sh, centers, km_metric, cap, n_lists, comms)
    mls = round_mls(int(counts_np.max()), sl._GROUP)

    # --- phase 2 (SPMD): level-encode + pack at the common padded size -----
    l2 = params.metric in ("sqeuclidean", "euclidean")
    rc = linalg.rotate_rows(centers, rotation, params.rotation_kind)
    c2 = dist_mod.sqnorm(centers)

    def pack_body(rows, ids, labels):
        rows, ids, labels = rows[0], ids[0], labels[0]
        safe = jnp.minimum(labels, n_lists - 1)
        codes, scale, row_bias = sl._encode_math(
            rows, safe, centers, rotation, rc, c2, l2, params.bits,
            params.rotation_kind)
        lc, li, lscale, lbias = scatter_pack(
            labels,
            [(jnp.zeros((n_lists, mls, nb), jnp.uint8), codes),
             (jnp.full((n_lists, mls), -1, jnp.int32), ids),
             (jnp.zeros((n_lists, mls), jnp.float32), scale),
             (jnp.zeros((n_lists, mls), jnp.float32), row_bias)],
            n_lists, mls)
        lbias = jnp.where(li >= 0, lbias, jnp.inf)
        return lc[None], li[None], lscale[None], lbias[None]

    pack_fn = jax.jit(shard_map(
        pack_body, mesh=comms.mesh,
        in_specs=(P(axis, None, None), P(axis, None), P(axis, None)),
        out_specs=(P(axis, None, None, None), P(axis, None, None),
                   P(axis, None, None), P(axis, None, None)),
        check_vma=False,
    ))
    list_codes, list_ids, list_scale, bias = pack_fn(work_sh, gids_sh,
                                                     labels_sh)
    return ShardedIvfBqIndex(
        centers, rotation, list_codes, list_ids, list_scale, bias,
        params.metric, n, comms, counts_np.max(axis=0).astype(np.int32),
        params.bits, params.rotation_kind,
    )


@traced("distributed.ivf_bq::search")
def search(
    index: ShardedIvfBqIndex,
    queries,
    k: int,
    n_probes: int = 20,
    res: Optional[Resources] = None,
    health=None,
) -> Tuple[jax.Array, jax.Array]:
    """SPMD IVF-BQ search over the sharded 1-bit code lists. Returns
    ESTIMATED (distances (q, k), global row ids (q, k)) as a
    :class:`~raft_tpu.distributed._sharding.SearchResult` (replicated;
    carries ``coverage``/``degraded`` when shards were dropped) — re-rank
    with neighbors/refine for the recall-gated configuration."""
    from raft_tpu.distributed._sharding import SearchResult, tiled_search
    from raft_tpu.ops.strip_scan import strip_eligible

    res = res or current_resources()
    queries = jnp.asarray(queries).astype(jnp.float32)
    if queries.shape[1] != index.dim:
        raise ValueError(f"query dim {queries.shape[1]} != index dim {index.dim}")
    if index.metric == "cosine":
        queries = queries / jnp.maximum(
            jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-30)
    n_probes = int(min(n_probes, index.n_lists))
    l2 = index.metric in ("sqeuclidean", "euclidean")

    probes, qr, _, pair_const = sl._bq_search_prep(
        queries, index.centers, index.rotation,
        jnp.zeros((1, 1), jnp.float32), jnp.full((1, 1), -1, jnp.int32),
        None, n_probes, index.metric, "exact", res.compute_dtype, l2,
        index.bits, index.rotation_kind,
    )
    # dense packed scan off-TPU: the interpreted kernel serializes
    # virtual-mesh shards (see distributed/ivf_flat.py)
    interpret = jax.default_backend() != "tpu"
    vals, ids, report = tiled_search(
        qr, probes, index.lens_max, index.n_lists, int(k), index.comms,
        -2.0 if l2 else -1.0,
        dense=interpret or not strip_eligible(index.max_list_size),
        interpret=interpret,
        data=index.list_codes, ids_arr=index.list_ids, bias=index.bias,
        pair_const=pair_const, algo="ivf_bq", n_total=index.n_total,
        health=health, scale=index.list_scale, scan="bq",
    )
    # the same finalize protocol the single-host fused path uses — one
    # shared copy, so distance conventions cannot drift between the
    # single-host and distributed BQ estimates
    from raft_tpu.neighbors.ivf_flat import _finalize_ragged

    vals, ids = _finalize_ragged(vals, ids, queries, index.metric)
    return SearchResult(vals, ids, coverage=report.coverage,
                        degraded=report.degraded,
                        lost_shards=report.dropped)
