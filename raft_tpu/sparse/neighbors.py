"""Sparse nearest neighbors: brute-force kNN over CSR + kNN-graph builder
(reference sparse/neighbors/brute_force.cuh, sparse/neighbors/knn_graph.cuh,
sparse/neighbors/cross_component_nn.cuh).

Search composes sparse/distance.py's densify-by-tiles MXU path with the
shared ``select_k`` primitive — the same two-stage tile/merge structure as
dense brute force (neighbors/detail/knn_brute_force.cuh:61 analog).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.ops.select_k import select_k
from raft_tpu.sparse import distance as sp_distance
from raft_tpu.sparse.types import COO, CSR


def brute_force_knn(
    index: CSR,
    queries: CSR,
    k: int,
    metric: str = "sqeuclidean",
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN of sparse queries against a sparse index
    (sparse/neighbors/brute_force.cuh analog). Returns (dists, ids) (q, k)."""
    res = res or current_resources()
    if not 0 < k <= index.shape[0]:
        raise ValueError(f"k={k} out of range for {index.shape[0]} index rows")
    d = sp_distance.pairwise_distance(queries, index, metric, res=res)
    return select_k(d, k)


def knn_graph(
    dataset,
    k: int,
    metric: str = "sqeuclidean",
    res: Optional[Resources] = None,
) -> COO:
    """Dense dataset → symmetric kNN adjacency as COO
    (sparse/neighbors/knn_graph.cuh analog; feeds MST/single-linkage).

    Each row contributes its k nearest *other* rows (self-edge excluded, like
    the reference); the directed edge list is then symmetrized with max-dedup
    (sparse/linalg/symmetrize.cuh analog) so downstream Borůvka sees an
    undirected, duplicate-free graph. Capacity = 2·n·k.
    """
    from raft_tpu.neighbors import brute_force

    res = res or current_resources()
    dataset = jnp.asarray(dataset)
    n = dataset.shape[0]
    if not 0 < k < n:
        raise ValueError(f"need 0 < k < n_rows, got k={k}, n={n}")
    bf = brute_force.build(dataset, metric=metric)
    dists, ids = brute_force.search(bf, dataset, k + 1, res=res)
    # drop each row's self column (it may not be at position 0 under ties):
    # mask self matches, then keep the k best of the remaining k+1
    rows = jnp.arange(n, dtype=jnp.int32)
    self_mask = ids == rows[:, None]
    dists = jnp.where(self_mask, jnp.inf, dists)
    dists, sub = jax.lax.top_k(-dists, k)
    dists = -dists
    ids = jnp.take_along_axis(ids, sub, axis=1)

    src = jnp.repeat(rows, k)
    dst = ids.reshape(-1)
    w = dists.reshape(-1).astype(jnp.float32)
    valid = dst >= 0
    from raft_tpu.sparse.linalg import symmetrize

    directed = COO(jnp.where(valid, src, -1), jnp.where(valid, dst, 0),
                   jnp.where(valid, w, 0), (n, n))
    return symmetrize(directed, mode="max")
