"""Sparse containers: COO and CSR with static-shape (padded) storage.

Reference surface: the owning sparse matrix types
(core/device_coo_matrix.hpp, core/device_csr_matrix.hpp, core/sparse_types.hpp).

TPU design — static nnz with sentinel padding. The reference's containers own
a runtime-sized nnz; under XLA every shape is static, so a container carries a
*capacity* (the array length) and marks unused tail entries with row ``-1``
(COO) / entries beyond ``indptr[-1]`` (CSR). All kernels treat padding as
"contributes zero": padded ``vals`` are stored as 0 and padded indices clipped
into range before gathers. This is the same padding-over-pointers trade every
dense structure in this framework makes (see neighbors/_packing.py).

Both containers are registered pytrees, so they jit/vmap/shard like arrays.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
@dataclass
class COO:
    """Coordinate-format sparse matrix (core/device_coo_matrix.hpp analog).

    ``rows``/``cols``/``vals`` are (capacity,) arrays; entries with
    ``rows < 0`` are padding and must carry ``vals == 0``.
    """

    rows: jax.Array
    cols: jax.Array
    vals: jax.Array
    shape: Tuple[int, int]

    @property
    def capacity(self) -> int:
        return self.rows.shape[0]

    @property
    def valid(self) -> jax.Array:
        """(capacity,) bool mask of real (non-padding) entries."""
        return self.rows >= 0

    def nnz(self) -> jax.Array:
        """Traced count of real entries."""
        return jnp.sum(self.valid.astype(jnp.int32))

    def tree_flatten(self):
        return (self.rows, self.cols, self.vals), (self.shape,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, shape=aux[0])

    def to_dense(self) -> jax.Array:
        """Densify; duplicate coordinates sum (scatter-add semantics)."""
        n, m = self.shape
        r = jnp.clip(self.rows, 0, n - 1)
        c = jnp.clip(self.cols, 0, m - 1)
        out = jnp.zeros((n, m), self.vals.dtype)
        v = jnp.where(self.valid, self.vals, 0)
        return out.at[r, c].add(v)


@jax.tree_util.register_pytree_node_class
@dataclass
class CSR:
    """Compressed-sparse-row matrix (core/device_csr_matrix.hpp analog).

    ``indptr`` is (n_rows+1,); ``indices``/``data`` are (capacity,) with the
    real entries in the first ``indptr[-1]`` positions (padding after: data 0,
    indices clipped in-range).
    """

    indptr: jax.Array
    indices: jax.Array
    data: jax.Array
    shape: Tuple[int, int]

    @property
    def capacity(self) -> int:
        return self.indices.shape[0]

    def nnz(self) -> jax.Array:
        return self.indptr[-1]

    def row_ids(self) -> jax.Array:
        """(capacity,) row id per entry — the CSR expand primitive every
        segment-reduction kernel keys on; padding entries get ``n_rows``
        (one-past-the-end segment)."""
        n = self.shape[0]
        pos = jnp.arange(self.capacity, dtype=self.indptr.dtype)
        rid = jnp.searchsorted(self.indptr, pos, side="right") - 1
        return jnp.where(pos < self.indptr[-1], rid, n).astype(jnp.int32)

    def tree_flatten(self):
        return (self.indptr, self.indices, self.data), (self.shape,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, shape=aux[0])

    def to_dense(self) -> jax.Array:
        n, m = self.shape
        rid = jnp.clip(self.row_ids(), 0, n - 1)
        cid = jnp.clip(self.indices, 0, m - 1)
        pos = jnp.arange(self.capacity)
        v = jnp.where(pos < self.indptr[-1], self.data, 0)
        return jnp.zeros((n, m), self.data.dtype).at[rid, cid].add(v)


def coo_from_dense(dense, capacity: int | None = None) -> COO:
    """Extract non-zeros from a concrete dense matrix (host path — nnz is a
    data-dependent shape, so this runs outside jit; sparse/convert/dense_to_*
    analog)."""
    d = np.asarray(dense)
    r, c = np.nonzero(d)
    v = d[r, c]
    cap = int(capacity) if capacity is not None else max(1, len(r))
    if len(r) > cap:
        raise ValueError(f"capacity {cap} < nnz {len(r)}")
    pad = cap - len(r)
    rows = np.concatenate([r.astype(np.int32), np.full(pad, -1, np.int32)])
    cols = np.concatenate([c.astype(np.int32), np.zeros(pad, np.int32)])
    vals = np.concatenate([v, np.zeros(pad, v.dtype)])
    return COO(jnp.asarray(rows), jnp.asarray(cols), jnp.asarray(vals), d.shape)


def csr_from_dense(dense, capacity: int | None = None) -> CSR:
    """Host-path dense → CSR (sparse/convert analog)."""
    from raft_tpu.sparse.convert import coo_to_csr

    return coo_to_csr(coo_from_dense(dense, capacity))


def coo_from_parts(rows, cols, vals, shape: Tuple[int, int]) -> COO:
    """Wrap raw coordinate arrays (validated) into a COO."""
    rows = jnp.asarray(rows, jnp.int32)
    cols = jnp.asarray(cols, jnp.int32)
    vals = jnp.asarray(vals)
    if not rows.shape == cols.shape == vals.shape or rows.ndim != 1:
        raise ValueError("rows/cols/vals must be equal-length 1-D arrays")
    vals = jnp.where(rows >= 0, vals, 0)
    return COO(rows, cols, vals, (int(shape[0]), int(shape[1])))
