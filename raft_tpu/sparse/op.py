"""Sparse structural ops: sort, filter, slice, row ops (reference sparse/op/).

All ops preserve static capacity — "removed" entries become padding
(row -1 / zero data), never a reshape.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.sparse.convert import coo_sort, coo_to_csr, csr_to_coo
from raft_tpu.sparse.types import COO, CSR

sort = coo_sort  # sparse/op/sort.h


def filter_entries(coo: COO, keep_mask) -> COO:
    """Mask out entries (sparse/op/filter.cuh analog): entries where
    ``keep_mask`` is False become padding, then re-sort pushes them to the
    end. Capacity unchanged."""
    keep = jnp.asarray(keep_mask, bool) & coo.valid
    return coo_sort(COO(jnp.where(keep, coo.rows, -1),
                        jnp.where(keep, coo.cols, 0),
                        jnp.where(keep, coo.vals, 0), coo.shape))


def remove_scalar(coo: COO, scalar=0.0) -> COO:
    """Drop entries equal to ``scalar`` (sparse/op/filter.cuh
    remove_scalar analog)."""
    return filter_entries(coo, coo.vals != scalar)


def slice_rows(csr: CSR, start: int, stop: int) -> CSR:
    """Row-range slice [start, stop) with the same capacity
    (sparse/op/slice.h analog). Entry positions shift so the slice's data
    occupies the first ``new_nnz`` slots."""
    n, m = csr.shape
    start, stop = int(start), int(stop)
    if not 0 <= start <= stop <= n:
        raise ValueError(f"bad slice [{start}, {stop}) for {n} rows")
    lo, hi = csr.indptr[start], csr.indptr[stop]
    pos = jnp.arange(csr.capacity, dtype=csr.indptr.dtype)
    src = jnp.clip(pos + lo, 0, csr.capacity - 1)
    in_slice = pos < (hi - lo)
    indices = jnp.where(in_slice, csr.indices[src], 0)
    data = jnp.where(in_slice, csr.data[src], 0)
    indptr = jnp.clip(
        jax.lax.dynamic_slice_in_dim(csr.indptr, start, stop - start + 1) - lo,
        0, hi - lo,
    ) if stop > start else jnp.zeros(1, csr.indptr.dtype)
    return CSR(indptr, indices, data, (stop - start, m))


def row_scale(csr: CSR, scales) -> CSR:
    """Scale each row by ``scales[row]`` (sparse/op/row_op.cuh analog)."""
    scales = jnp.asarray(scales)
    rid = jnp.clip(csr.row_ids(), 0, csr.shape[0] - 1)
    return CSR(csr.indptr, csr.indices, csr.data * scales[rid], csr.shape)


__all__ = ["sort", "filter_entries", "remove_scalar", "slice_rows",
           "row_scale", "coo_to_csr", "csr_to_coo"]
