"""Sparse solvers: Borůvka MST and a Lanczos eigensolver
(reference sparse/solver/mst_solver.cuh:40, sparse/solver/lanczos.cuh:68).

MST — TPU design. The reference's MST is a Borůvka variant with per-vertex
atomics for min-edge selection and a union-find over device memory. Atomics
and pointer-chasing unions don't map to XLA, so every phase here is a
vectorized reduction over static shapes:

  * min outgoing edge per component  → ``segment_min`` keyed on the
    component color of each edge's source endpoint (both directions of every
    undirected edge are present, so one side suffices). The selection key is
    the composite ``(weight, min(colors), max(colors), entry index)`` —
    crucially identical for *both directions* of an undirected edge, which
    makes the order globally consistent: a choice-graph cycle longer than 2
    would need every edge on it to share the same key, hence the same
    component pair, hence be a 2-cycle. So only mutual pairs need breaking
    (the smaller color becomes the root and drops its edge);
  * contraction → plain pointer jumping ``p ← p∘p`` on the now-cycle-free
    parent array, then relabel every vertex color through it; repeat until
    no component has an outgoing edge.

Rounds are O(log n); each round is sorts/segment-reductions/gathers the VPU
vectorizes. Output is a fixed (n-1)-slot edge buffer + a traced count
(forests of disconnected graphs fill fewer slots; unused slots are -1).
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.sparse.linalg import spmv
from raft_tpu.sparse.types import COO, CSR


class MstResult(NamedTuple):
    """MST/forest edges (sparse/solver/mst_solver.cuh Graph_COO analog)."""

    src: jax.Array      # (n-1,) int32, -1 beyond n_edges
    dst: jax.Array      # (n-1,) int32
    weight: jax.Array   # (n-1,) float32, 0 beyond n_edges
    n_edges: jax.Array  # scalar int32
    color: jax.Array    # (n,) final component label per vertex


def _pointer_jump(p: jax.Array) -> jax.Array:
    """p ← p∘p to fixpoint (valid once the parent graph is a forest)."""

    def cond(state):
        p, changed = state
        return changed

    def body(state):
        p, _ = state
        p2 = p[p]
        return p2, jnp.any(p2 != p)

    p, _ = lax.while_loop(cond, body, (p, jnp.array(True)))
    return p


@functools.partial(jax.jit, static_argnames=("n",))
def _mst_impl(rows, cols, vals, valid, n: int):
    E = rows.shape[0]
    INF = jnp.inf
    out_src = jnp.full(max(n - 1, 1), -1, jnp.int32)
    out_dst = jnp.full(max(n - 1, 1), -1, jnp.int32)
    out_w = jnp.zeros(max(n - 1, 1), jnp.float32)
    color = jnp.arange(n, dtype=jnp.int32)
    count = jnp.zeros((), jnp.int32)

    def cond(state):
        _, _, _, _, _, changed = state
        return changed

    def body(state):
        color, out_src, out_dst, out_w, count, _ = state
        cu = color[jnp.clip(rows, 0, n - 1)]
        cv = color[jnp.clip(cols, 0, n - 1)]
        live = valid & (cu != cv)

        # min outgoing edge per component under the direction-symmetric key
        # (w, min(cu,cv), max(cu,cv), idx) — lexicographic via cascaded
        # segment_min passes
        key = jnp.where(live, cu, n).astype(jnp.int32)
        cmin = jnp.minimum(cu, cv)
        cmax = jnp.maximum(cu, cv)

        w_live = jnp.where(live, vals, INF)
        minw = jax.ops.segment_min(w_live, key, num_segments=n + 1)[:n]
        sel = live & (vals == minw[jnp.clip(cu, 0, n - 1)])
        mcmin = jax.ops.segment_min(
            jnp.where(sel, cmin, n), key, num_segments=n + 1)[:n]
        sel &= cmin == mcmin[jnp.clip(cu, 0, n - 1)]
        mcmax = jax.ops.segment_min(
            jnp.where(sel, cmax, n), key, num_segments=n + 1)[:n]
        sel &= cmax == mcmax[jnp.clip(cu, 0, n - 1)]
        eidx = jax.ops.segment_min(
            jnp.where(sel, jnp.arange(E, dtype=jnp.int32), E),
            key, num_segments=n + 1,
        )[:n]
        has_edge = eidx < E
        e = jnp.clip(eidx, 0, E - 1)
        c_ids = jnp.arange(n, dtype=jnp.int32)
        t = jnp.where(has_edge, cv[e], c_ids)

        # break mutual pairs (the only possible cycles): smaller color roots
        mutual = t[t] == c_ids
        is_root = ~has_edge | (mutual & (c_ids < t))
        p = jnp.where(is_root, c_ids, t)
        p = _pointer_jump(p)
        keep = has_edge & ~is_root

        # append kept edges at positions [count, count + n_kept)
        pos = count + jnp.cumsum(keep.astype(jnp.int32)) - 1
        pos = jnp.where(keep, jnp.clip(pos, 0, out_src.shape[0] - 1),
                        out_src.shape[0])  # OOB -> dropped by mode="drop"
        out_src = out_src.at[pos].set(rows[e], mode="drop")
        out_dst = out_dst.at[pos].set(cols[e], mode="drop")
        out_w = out_w.at[pos].set(vals[e].astype(jnp.float32), mode="drop")
        n_kept = jnp.sum(keep.astype(jnp.int32))

        return p[color], out_src, out_dst, out_w, count + n_kept, n_kept > 0

    color, out_src, out_dst, out_w, count, _ = lax.while_loop(
        cond, body, (color, out_src, out_dst, out_w, count, jnp.array(True))
    )
    return out_src, out_dst, out_w, count, color


def mst(graph: COO) -> MstResult:
    """Minimum spanning tree/forest of a symmetric weighted COO graph
    (sparse/solver/mst.cuh:59 analog — the single-linkage substrate).

    ``graph`` must contain both directions of every undirected edge (as
    :func:`raft_tpu.sparse.neighbors.knn_graph` and
    :func:`raft_tpu.sparse.linalg.symmetrize` produce).
    """
    n, m = graph.shape
    if n != m:
        raise ValueError(f"graph must be square, got {graph.shape}")
    if n < 2:
        raise ValueError("graph needs at least 2 vertices")
    src, dst, w, cnt, color = _mst_impl(
        graph.rows, graph.cols, graph.vals, graph.valid, n
    )
    return MstResult(src, dst, w, cnt, color)


def connected_components(graph: COO) -> jax.Array:
    """Per-vertex component labels via the same contraction machinery
    (sparse/neighbors/cross_component_nn.cuh's connectivity sub-primitive)."""
    return mst(graph).color


# ---------------------------------------------------------------------------
# Lanczos
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("matvec", "n", "max_iters"))
def _lanczos_impl(matvec, n: int, max_iters: int, v0, U):
    """One Lanczos run of the deflated operator P·A·P with P = I − U·Uᵀ.

    ``U`` is a traced (n, k) deflation basis — zero columns are no-ops, so
    callers pad it to a fixed width. It must be traced, NOT closure-captured:
    jit hashes a static callable by id(), and Python id reuse across
    successively created closures can silently replay a stale trace.
    """
    m = max_iters

    v0 = v0 / jnp.linalg.norm(v0)
    V = jnp.zeros((m, n), jnp.float32).at[0].set(v0)

    def step(carry, i):
        V, beta_prev = carry
        v = V[i]
        w = matvec(v - U @ (U.T @ v))
        w = w - U @ (U.T @ w)
        alpha = jnp.dot(w, v)
        w = w - alpha * v - beta_prev * V[jnp.maximum(i - 1, 0)] * (i > 0)
        # full reorthogonalization against all previous vectors (the
        # reference re-orthogonalizes too, sparse/solver/detail/lanczos.cuh):
        # rows past i are zero so the correction is a masked gemv pair
        w = w - V.T @ (V @ w)
        # deflation scrub LAST: the reorthogonalization can reintroduce
        # U-components through drift in earlier rows, and any residue in
        # v_next compounds exponentially over the run
        w = w - U @ (U.T @ w)
        beta = jnp.linalg.norm(w)
        v_next = jnp.where(beta > 1e-10, w / jnp.maximum(beta, 1e-30),
                           jnp.zeros_like(w))
        V = V.at[jnp.minimum(i + 1, m - 1)].set(
            jnp.where(i + 1 < m, v_next, V[m - 1])
        )
        return (V, beta), (alpha, beta)

    (V, _), (alphas, betas) = lax.scan(step, (V, jnp.zeros((), jnp.float32)),
                                       jnp.arange(m))
    return V, alphas, betas


def lanczos_smallest(
    a: Union[CSR, Callable],
    n_components: int,
    n: Optional[int] = None,
    max_iters: int = 0,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Smallest eigenpairs of a symmetric operator
    (sparse/solver/lanczos.cuh:68 analog, used by spectral/).

    ``a``: a CSR matrix or a matvec callable (jit-traceable). Returns
    ``(eigenvalues (k,), eigenvectors (n, k))``.
    """
    if isinstance(a, CSR):
        if a.shape[0] != a.shape[1]:
            raise ValueError("operator must be square")
        n = a.shape[0]
        csr = a

        def matvec(v):
            return spmv(csr, v)
    else:
        if n is None:
            raise ValueError("n is required when `a` is a callable")
        matvec = a
    k = int(n_components)
    if not 0 < k <= n:
        raise ValueError(f"need 0 < n_components <= {n}")
    m = int(max_iters) if max_iters else min(n, max(4 * k, 32))
    m = min(m, n)

    # sequential deflation: a Krylov space from one start vector contains at
    # most ONE eigenvector per degenerate eigenvalue (e.g. the c-fold zero
    # eigenvalue of a c-component graph Laplacian), so each eigenpair gets
    # its own run with previously-found directions projected out of the
    # operator (the reference restarts its Lanczos the same way,
    # sparse/solver/detail/lanczos.cuh computeSmallestEigenvectors restarts)
    found_vals, found_vecs = [], []
    key = jax.random.key(seed)
    for j in range(k):
        key, k_v0 = jax.random.split(key)
        # fixed-width deflation basis: unfound columns stay zero (no-op)
        U = jnp.zeros((n, k), jnp.float32)
        for jj, u in enumerate(found_vecs):
            U = U.at[:, jj].set(u)

        v0 = jax.random.normal(k_v0, (n,), jnp.float32)
        v0 = v0 - U @ (U.T @ v0)
        V, alphas, betas = _lanczos_impl(matvec, n, m, v0, U)
        # happy breakdown: once some beta ~ 0 the Krylov space is exhausted
        # and later (alpha, beta) are garbage zeros — push those diagonal
        # entries to +huge so eigh ranks them last instead of as spurious
        # smallest eigenvalues
        good = jnp.concatenate([
            jnp.array([True]),
            jnp.cumprod((betas[:-1] > 1e-8).astype(jnp.int32)).astype(bool),
        ])
        alphas = jnp.where(good, alphas, 1e30)
        offd = jnp.where(good[1:], betas[:-1], 0.0)
        T = jnp.diag(alphas) + jnp.diag(offd, 1) + jnp.diag(offd, -1)
        evals, S = jnp.linalg.eigh(T)
        vec = V.T @ S[:, 0]
        vec = vec / jnp.maximum(jnp.linalg.norm(vec), 1e-30)
        found_vals.append(evals[0])
        found_vecs.append(vec)
    order = jnp.argsort(jnp.stack(found_vals))
    vals = jnp.stack(found_vals)[order]
    vecs = jnp.stack(found_vecs, axis=1)[:, order]
    return vals, vecs
