"""Sparse pairwise distances (reference sparse/distance/distance.cuh).

TPU design — densify-by-tiles, then ride the dense MXU path. The reference
implements sparse distances as COO-SpMV expansions with hash/bloom strategies
(sparse/distance/detail/coo_spmv.cuh) because GPU gathers on CSR are cheap
and dense FLOPs on mostly-zero rows are not. On TPU the economics invert:
the MXU turns a dense (tile x dim) x (dim x n) product into the cheapest op
in the machine, while data-dependent sparse gathers fight the vector unit.
So each row tile of X (and Y) is scattered into a dense block once, and every
metric reuses :mod:`raft_tpu.ops.distance` unchanged — one code path, every
dense metric supported, zero sparse-specific kernels to validate.

For feature spaces too wide to densify (dim beyond ~1e5 at fp32), tiles
shrink along rows first; the dim axis itself can be chunked for the
inner-product family via accumulation, which covers the expanded metrics
(l2/ip/cosine) that dominate sparse-kNN workloads.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.ops import distance as dense_distance
from raft_tpu.sparse.types import CSR


def _densify_rows(csr: CSR, start, n_rows_tile: int) -> jax.Array:
    """Scatter a row tile [start, start+n_rows_tile) into a dense block."""
    n, m = csr.shape
    rid = csr.row_ids()
    local = rid - start
    in_tile = (local >= 0) & (local < n_rows_tile)
    local = jnp.clip(local, 0, n_rows_tile - 1)
    cid = jnp.clip(csr.indices, 0, m - 1)
    v = jnp.where(in_tile, csr.data, 0)
    return jnp.zeros((n_rows_tile, m), csr.data.dtype).at[local, cid].add(v)


def _to_ell(csr: CSR, width_round: int = 8):
    """CSR → padded ELL: (cols (n, w), vals (n, w)) with w = max row nnz
    rounded up. Static shapes (padding cols point at column 0 with value 0),
    so every downstream op is a fixed-shape gather/reduce — the TPU
    replacement for per-row variable-length iteration."""
    n, m = csr.shape
    rid = csr.row_ids()
    counts = jnp.bincount(rid, length=n)
    w = int(jnp.max(counts)) if csr.indices.shape[0] else 1
    w = max(width_round, -(-w // width_round) * width_round)
    offsets = csr.indptr[:-1]
    pos = jnp.arange(csr.indices.shape[0], dtype=jnp.int32) - offsets[rid]
    cols = jnp.zeros((n, w), jnp.int32).at[rid, pos].set(
        jnp.clip(csr.indices, 0, m - 1))
    vals = jnp.zeros((n, w), csr.data.dtype).at[rid, pos].set(csr.data)
    return cols, vals, w


def _expand_ip(x: CSR, y: CSR, res) -> jax.Array:
    """Sparse×sparse inner products via nnz expansion — the COO-SpMV
    analog (reference sparse/distance/detail/coo_spmv.cuh hash strategy),
    recast for TPU: x rides a padded ELL layout, y a transposed dense
    tile, and each x-row's ⟨x, y_j⟩ is one fixed-width gather + contraction

        ip[i, :] = Σ_k vals[i, k] · Yᵀ[cols[i, k], :]

    Work is nx·w·ny (w = max row nnz) instead of the dense path's
    nx·m·ny — at ≥95% sparsity the ~20× FLOP reduction beats the MXU's
    unit-cost advantage on wide feature spaces. Static shapes throughout:
    no scatter, no segment ops (padding contributes exact zeros)."""
    nx, m = x.shape
    ny = y.shape[0]
    cols, vals, w = _to_ell(x)
    # y transposed dense tile: (m, ny_tile); the gather below reads rows
    y_bytes = m * ny * 4
    ny_tile = (ny if y_bytes <= res.workspace_bytes // 4
               else max(1, (res.workspace_bytes // 4) // max(m * 4, 1)))
    # x tile bounds the (tile, w, ny_tile) gathered block
    per_row = max(1, w * ny_tile * 4 * 2)
    x_tile = int(max(1, min(nx, (res.workspace_bytes // 2) // per_row)))

    out_rows = []
    for sx in range(0, nx, x_tile):
        tx = min(x_tile, nx - sx)
        c_t = jax.lax.slice_in_dim(cols, sx, sx + tx, axis=0)
        v_t = jax.lax.slice_in_dim(vals, sx, sx + tx, axis=0)
        cols_out = []
        for sy in range(0, ny, ny_tile):
            ty = min(ny_tile, ny - sy)
            yT = _densify_rows(y, sy, ty).T              # (m, ty)
            g = yT[c_t.reshape(-1)].reshape(tx, w, ty)   # (tx, w, ty)
            cols_out.append(jnp.einsum(
                "rk,rkn->rn", v_t, g, preferred_element_type=jnp.float32))
        out_rows.append(jnp.concatenate(cols_out, axis=1)
                        if len(cols_out) > 1 else cols_out[0])
    return jnp.concatenate(out_rows, axis=0) if len(out_rows) > 1 else out_rows[0]


def _row_sqnorms(csr: CSR) -> jax.Array:
    n = csr.shape[0]
    return jax.ops.segment_sum(csr.data * csr.data, csr.row_ids(),
                               num_segments=n)


_EXPAND_METRICS = ("sqeuclidean", "euclidean", "inner_product", "cosine")


def pairwise_distance(
    x: CSR,
    y: Optional[CSR] = None,
    metric: str = "sqeuclidean",
    p: float = 2.0,
    res: Optional[Resources] = None,
    backend: str = "auto",
) -> jax.Array:
    """All-pairs (x_rows, y_rows) distance matrix between CSR operands.

    Any metric of :func:`raft_tpu.ops.distance.pairwise_distance` is valid
    (superset of the reference's sparse metric list,
    sparse/distance/distance.cuh).

    ``backend``:

    * ``"auto"`` — ALWAYS the dense route. This is a decided, measured
      policy, not a heuristic that might pick "expand".
    * ``"dense"`` — densify-by-tiles + MXU; every metric. The measured
      winner on TPU at every sparsity tested, down to 99.8% sparse at
      (2048² × 16384) — see results/SPARSE_r04.json.
    * ``"expand"`` — nnz-expansion over a padded ELL layout (the coo_spmv
      analog; l2/ip/cosine only). **Oracle / API-parity only — measured
      SLOWER than dense at every tested shape and sparsity (0.04–0.33×)**,
      and the loss is bandwidth-fundamental on this hardware: the gathered
      (rows, nnz_width, ny) block round-trips HBM, which costs as much
      memory traffic as the dense pass costs MXU FLOPs, and per-row
      gathers are op-bound (~12 ns/row) besides. Kept as an independent
      correctness oracle for the dense path and as the slot where a host
      (CPU) offload variant would plug in; do not use it for performance.
    """
    res = res or current_resources()
    y = x if y is None else y
    if x.shape[1] != y.shape[1]:
        raise ValueError(f"dim mismatch: {x.shape} vs {y.shape}")
    if backend not in ("auto", "dense", "expand"):
        raise ValueError(f"unknown sparse distance backend {backend!r}")
    nx, m = x.shape
    ny = y.shape[0]

    canon = dense_distance.canonical_metric(metric)
    if backend == "expand" and canon not in _EXPAND_METRICS:
        raise ValueError(
            f"backend='expand' supports {_EXPAND_METRICS}, got {metric!r} "
            "(use backend='dense')")
    # measured (results/SPARSE_r04.json, v5e): the expand path LOSES to
    # the dense MXU route at every tested density down to 99.8% sparse
    # at (2048² × 16384) — TPU row gathers are op-bound (~12 ns/row),
    # so nnz-expansion pays per-gather what the MXU amortizes away.
    # "auto" therefore always takes dense; "expand" stays available for
    # explicit use (API parity with the coo_spmv strategy family, and the
    # place a future host-offload variant would slot in).
    if backend == "expand" and nx and ny:
        ip = _expand_ip(x, y, res)
        if canon == "inner_product":
            return ip
        xs = _row_sqnorms(x)
        ys = _row_sqnorms(y)
        if canon == "cosine":
            denom = jnp.sqrt(jnp.maximum(
                xs[:, None] * ys[None, :], 1e-30))
            return 1.0 - ip / denom
        d = jnp.maximum(xs[:, None] + ys[None, :] - 2.0 * ip, 0.0)
        return jnp.sqrt(d) if canon == "euclidean" else d

    # densify-by-tiles strategy: BOTH operands are materialized densely only
    # in workspace-bounded tiles (round-2 review: y was densified whole,
    # which is quadratic-memory wrong for the wide matrices the reference's
    # hash-strategy SpMV serves, coo_spmv_strategies/hash_strategy.cuh)
    if nx == 0 or ny == 0:
        return jnp.zeros((nx, ny), jnp.float32)
    y_bytes = ny * m * 4
    if y_bytes <= res.workspace_bytes // 2:
        y_tile = ny
    else:
        y_tile = int(max(1, (res.workspace_bytes // 2) // max(m * 4, 1)))
    # the x tile holds full ny-wide output rows until the axis-1 concat, so
    # size it against ny (not y_tile)
    bytes_per_row = max(1, (m + ny) * 4 * 2)
    tile = int(max(1, min(nx, (res.workspace_bytes // 2) // bytes_per_row)))

    # hoist the densification when y fits whole (the common case) so the
    # O(nnz(y)) scatter runs once, not once per x tile
    yd_whole = _densify_rows(y, 0, ny) if y_tile == ny else None

    rows = []
    for s in range(0, nx, tile):
        t = min(tile, nx - s)
        xd = _densify_rows(x, s, t)
        cols = []
        for sy in range(0, ny, y_tile):
            ty = min(y_tile, ny - sy)
            yd = yd_whole if yd_whole is not None else _densify_rows(y, sy, ty)
            cols.append(dense_distance.pairwise_distance(xd, yd, metric, p=p,
                                                         res=res))
        rows.append(jnp.concatenate(cols, axis=1) if len(cols) > 1 else cols[0])
    return jnp.concatenate(rows, axis=0) if len(rows) > 1 else rows[0]
