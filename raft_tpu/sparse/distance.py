"""Sparse pairwise distances (reference sparse/distance/distance.cuh).

TPU design — densify-by-tiles, then ride the dense MXU path. The reference
implements sparse distances as COO-SpMV expansions with hash/bloom strategies
(sparse/distance/detail/coo_spmv.cuh) because GPU gathers on CSR are cheap
and dense FLOPs on mostly-zero rows are not. On TPU the economics invert:
the MXU turns a dense (tile x dim) x (dim x n) product into the cheapest op
in the machine, while data-dependent sparse gathers fight the vector unit.
So each row tile of X (and Y) is scattered into a dense block once, and every
metric reuses :mod:`raft_tpu.ops.distance` unchanged — one code path, every
dense metric supported, zero sparse-specific kernels to validate.

For feature spaces too wide to densify (dim beyond ~1e5 at fp32), tiles
shrink along rows first; the dim axis itself can be chunked for the
inner-product family via accumulation, which covers the expanded metrics
(l2/ip/cosine) that dominate sparse-kNN workloads.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.ops import distance as dense_distance
from raft_tpu.sparse.types import CSR


def _densify_rows(csr: CSR, start, n_rows_tile: int) -> jax.Array:
    """Scatter a row tile [start, start+n_rows_tile) into a dense block."""
    n, m = csr.shape
    rid = csr.row_ids()
    local = rid - start
    in_tile = (local >= 0) & (local < n_rows_tile)
    local = jnp.clip(local, 0, n_rows_tile - 1)
    cid = jnp.clip(csr.indices, 0, m - 1)
    v = jnp.where(in_tile, csr.data, 0)
    return jnp.zeros((n_rows_tile, m), csr.data.dtype).at[local, cid].add(v)


def pairwise_distance(
    x: CSR,
    y: Optional[CSR] = None,
    metric: str = "sqeuclidean",
    p: float = 2.0,
    res: Optional[Resources] = None,
) -> jax.Array:
    """All-pairs (x_rows, y_rows) distance matrix between CSR operands.

    Any metric of :func:`raft_tpu.ops.distance.pairwise_distance` is valid
    (superset of the reference's sparse metric list,
    sparse/distance/distance.cuh).
    """
    res = res or current_resources()
    y = x if y is None else y
    if x.shape[1] != y.shape[1]:
        raise ValueError(f"dim mismatch: {x.shape} vs {y.shape}")
    nx, m = x.shape
    ny = y.shape[0]

    # densify-by-tiles strategy: BOTH operands are materialized densely only
    # in workspace-bounded tiles (round-2 review: y was densified whole,
    # which is quadratic-memory wrong for the wide matrices the reference's
    # hash-strategy SpMV serves, coo_spmv_strategies/hash_strategy.cuh)
    if nx == 0 or ny == 0:
        return jnp.zeros((nx, ny), jnp.float32)
    y_bytes = ny * m * 4
    if y_bytes <= res.workspace_bytes // 2:
        y_tile = ny
    else:
        y_tile = int(max(1, (res.workspace_bytes // 2) // max(m * 4, 1)))
    # the x tile holds full ny-wide output rows until the axis-1 concat, so
    # size it against ny (not y_tile)
    bytes_per_row = max(1, (m + ny) * 4 * 2)
    tile = int(max(1, min(nx, (res.workspace_bytes // 2) // bytes_per_row)))

    # hoist the densification when y fits whole (the common case) so the
    # O(nnz(y)) scatter runs once, not once per x tile
    yd_whole = _densify_rows(y, 0, ny) if y_tile == ny else None

    rows = []
    for s in range(0, nx, tile):
        t = min(tile, nx - s)
        xd = _densify_rows(x, s, t)
        cols = []
        for sy in range(0, ny, y_tile):
            ty = min(y_tile, ny - sy)
            yd = yd_whole if yd_whole is not None else _densify_rows(y, sy, ty)
            cols.append(dense_distance.pairwise_distance(xd, yd, metric, p=p,
                                                         res=res))
        rows.append(jnp.concatenate(cols, axis=1) if len(cols) > 1 else cols[0])
    return jnp.concatenate(rows, axis=0) if len(rows) > 1 else rows[0]
