"""COO <-> CSR <-> dense conversions (reference sparse/convert/).

All conversions are jit-safe (static capacity in, static capacity out); the
only host-side entry points are the ``*_from_dense`` constructors in
:mod:`raft_tpu.sparse.types`, where nnz is data-dependent.
"""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.sparse.types import COO, CSR


def coo_sort(coo: COO) -> COO:
    """Sort entries by (row, col), padding to the end (sparse/op/sort.h
    analog). Stable, fully vectorized (one key sort on the VPU)."""
    # two-key lexsort (row-major, padding last) — avoids a fused int64 key,
    # which would need x64 mode for large shapes
    prim = jnp.where(coo.valid, coo.rows, jnp.iinfo(jnp.int32).max)
    order = jnp.lexsort((coo.cols, prim))
    return COO(coo.rows[order], coo.cols[order], coo.vals[order], coo.shape)


def coo_to_csr(coo: COO) -> CSR:
    """COO → CSR of the same capacity (sparse/convert/csr.cuh analog)."""
    n, _ = coo.shape
    s = coo_sort(coo)
    counts = jnp.zeros(n, jnp.int32).at[jnp.clip(s.rows, 0, n - 1)].add(
        s.valid.astype(jnp.int32)
    )
    indptr = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(counts)])
    return CSR(indptr, jnp.maximum(s.cols, 0), jnp.where(s.valid, s.vals, 0),
               coo.shape)


def csr_to_coo(csr: CSR) -> COO:
    """CSR → COO of the same capacity (sparse/convert/coo.cuh analog)."""
    rid = csr.row_ids()
    valid = rid < csr.shape[0]
    rows = jnp.where(valid, rid, -1).astype(jnp.int32)
    return COO(rows, jnp.where(valid, csr.indices, 0),
               jnp.where(valid, csr.data, 0), csr.shape)
