"""Sparse linear algebra (reference sparse/linalg/).

TPU design — every kernel is a segment reduction keyed on the CSR row-expand
(``CSR.row_ids``), lowered by XLA to vectorized scatter-adds, plus dense
gathers from the operand. The reference's cuSPARSE SpMM/SpMV calls
(sparse/linalg/spmm.hpp) become ``segment_sum`` over gathered dense rows —
the multiply itself stays elementwise on the VPU; for matmul-dominant mixes
callers can densify tiles instead (see sparse/distance.py, which deliberately
routes through the MXU).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.sparse.convert import coo_sort, coo_to_csr, csr_to_coo
from raft_tpu.sparse.types import COO, CSR


def spmv(csr: CSR, x) -> jax.Array:
    """y = A @ x for CSR A and dense (m,) x (sparse/linalg/spmv wrapper)."""
    return spmm(csr, x[:, None])[:, 0]


def spmm(csr: CSR, B) -> jax.Array:
    """C = A @ B for CSR A (n,m) and dense B (m,k) (sparse/linalg/spmm.hpp).

    gather-rows + segment_sum formulation: padding entries key to segment n
    (dropped by num_segments) and carry zero data.
    """
    B = jnp.asarray(B)
    n, m = csr.shape
    if B.shape[0] != m:
        raise ValueError(f"B rows {B.shape[0]} != A cols {m}")
    rid = csr.row_ids()
    contrib = csr.data[:, None] * B[jnp.clip(csr.indices, 0, m - 1)]
    return jax.ops.segment_sum(contrib, rid, num_segments=n)


def transpose(coo: COO) -> COO:
    """A^T as COO (sparse/linalg/transpose.h analog)."""
    return coo_sort(COO(jnp.where(coo.valid, coo.cols, -1), jnp.maximum(coo.rows, 0),
                        coo.vals, (coo.shape[1], coo.shape[0])))


def add(a: COO, b: COO) -> COO:
    """A + B as COO with capacity ``a.capacity + b.capacity``; duplicate
    coordinates are kept (they sum in spmm/to_dense — scatter-add semantics,
    sparse/linalg/add.cuh analog)."""
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch {a.shape} vs {b.shape}")
    return coo_sort(COO(
        jnp.concatenate([a.rows, b.rows]),
        jnp.concatenate([a.cols, b.cols]),
        jnp.concatenate([a.vals, b.vals]),
        a.shape,
    ))


def symmetrize(coo: COO, mode: str = "max") -> COO:
    """Make A symmetric over the union pattern (sparse/linalg/symmetrize.cuh).

    mode 'max': S = elementwise max(A, A^T) — duplicate-free by construction:
    both directed copies of each edge are emitted with the max weight, and
    exact duplicates within the input are collapsed via a sorted-run mask.
    mode 'sum' / 'mean': S = A + A^T (/2), duplicates kept (scatter-add).
    """
    at = transpose(coo)
    if mode in ("sum", "mean"):
        out = add(coo, at)
        if mode == "mean":
            out = COO(out.rows, out.cols, out.vals * 0.5, out.shape)
        return out
    if mode != "max":
        raise ValueError(f"unknown mode {mode!r}")
    s = coo_sort(COO(
        jnp.concatenate([coo.rows, at.rows]),
        jnp.concatenate([coo.cols, at.cols]),
        jnp.concatenate([coo.vals, at.vals]),
        coo.shape,
    ))
    # collapse equal-coordinate runs to a single max-valued entry
    same_prev = (
        (s.rows == jnp.roll(s.rows, 1)) & (s.cols == jnp.roll(s.cols, 1))
    ).at[0].set(False)
    # run max via parallel segmented scan: (m, start) o (m', start') =
    # (start' ? m' : max(m, m'), start | start') — associative, O(log nnz)
    # depth instead of a sequential lax.scan
    def seg_op(a, b):
        return (jnp.where(b[1], b[0], jnp.maximum(a[0], b[0])), a[1] | b[1])

    run_max, _ = jax.lax.associative_scan(seg_op, (s.vals, ~same_prev))
    is_last = jnp.concatenate([~same_prev[1:], jnp.array([True])])
    keep = is_last & s.valid
    rows = jnp.where(keep, s.rows, -1)
    return coo_sort(COO(rows, jnp.maximum(s.cols, 0),
                        jnp.where(keep, run_max, 0), s.shape))


def degree(coo: COO) -> jax.Array:
    """Per-row non-zero count (sparse/linalg/degree.cuh analog)."""
    n = coo.shape[0]
    return jnp.zeros(n, jnp.int32).at[jnp.clip(coo.rows, 0, n - 1)].add(
        coo.valid.astype(jnp.int32)
    )


def row_norm(csr: CSR, norm: str = "l2") -> jax.Array:
    """Per-row L1/L2/Linf norms (sparse/linalg/norm.cuh analog)."""
    n = csr.shape[0]
    rid = csr.row_ids()
    if norm == "l1":
        return jax.ops.segment_sum(jnp.abs(csr.data), rid, num_segments=n)
    if norm == "l2":
        return jax.ops.segment_sum(csr.data * csr.data, rid, num_segments=n)
    if norm == "linf":
        # empty segments reduce to -inf; an all-zero row's Linf norm is 0
        return jnp.maximum(
            jax.ops.segment_max(jnp.abs(csr.data), rid, num_segments=n), 0
        )
    raise ValueError(f"unknown norm {norm!r}")


def laplacian(coo: COO, normalized: bool = False) -> COO:
    """Graph Laplacian L = D - A (or sym-normalized I - D^-1/2 A D^-1/2) as
    COO with capacity nnz + n (sparse/linalg/laplacian analog, feeds
    spectral/)."""
    n, m = coo.shape
    if n != m:
        raise ValueError("laplacian needs a square adjacency")
    deg_w = jnp.zeros(n, coo.vals.dtype).at[jnp.clip(coo.rows, 0, n - 1)].add(
        jnp.where(coo.valid, coo.vals, 0)
    )
    diag_r = jnp.arange(n, dtype=jnp.int32)
    if not normalized:
        off = COO(coo.rows, coo.cols, -coo.vals, coo.shape)
        dia = COO(diag_r, diag_r, deg_w, coo.shape)
    else:
        inv_sqrt = jnp.where(deg_w > 0, 1.0 / jnp.sqrt(jnp.maximum(deg_w, 1e-30)), 0.0)
        r = jnp.clip(coo.rows, 0, n - 1)
        c = jnp.clip(coo.cols, 0, n - 1)
        off = COO(coo.rows, coo.cols, -coo.vals * inv_sqrt[r] * inv_sqrt[c],
                  coo.shape)
        dia = COO(diag_r, diag_r, jnp.where(deg_w > 0, 1.0, 0.0).astype(coo.vals.dtype),
                  coo.shape)
    return add(off, dia)


__all__ = [
    "spmv", "spmm", "transpose", "add", "symmetrize", "degree", "row_norm",
    "laplacian", "coo_to_csr", "csr_to_coo",
]
