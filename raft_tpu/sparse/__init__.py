"""Sparse tier (reference cpp/include/raft/sparse/): COO/CSR containers,
conversions, structural ops, linalg, distances, neighbors, and solvers
(Borůvka MST, Lanczos) — all static-shape, padding-based (see types.py)."""

from raft_tpu.sparse import convert, distance, linalg, neighbors, op, solver
from raft_tpu.sparse.convert import coo_sort, coo_to_csr, csr_to_coo
from raft_tpu.sparse.solver import MstResult, connected_components, lanczos_smallest, mst
from raft_tpu.sparse.types import COO, CSR, coo_from_dense, coo_from_parts, csr_from_dense

__all__ = [
    "COO", "CSR", "MstResult",
    "convert", "distance", "linalg", "neighbors", "op", "solver",
    "coo_from_dense", "coo_from_parts", "csr_from_dense",
    "coo_sort", "coo_to_csr", "csr_to_coo",
    "connected_components", "lanczos_smallest", "mst",
]
